"""Micro-batched pipeline model parallelism on a mesh axis.

TPU-native replacement for the reference's RPC pipeline
(`model_parallel_ResNet50.py:142-184`): there, stages live on RPC workers,
micro-batches flow master→worker1→worker2 as RRefs with async futures, and
``dist_autograd``/``DistributedOptimizer`` stitch backward and the update
together across processes (`:222-225`).  Here the entire schedule is ONE
compiled SPMD program:

* the mesh has a ``stage`` axis; device column ``s`` executes stage ``s``;
* a GPipe fill-drain schedule runs as ``lax.scan`` over
  ``num_microbatches + num_stages - 1`` ticks;
* stage-to-stage activation transfer is ``lax.ppermute`` over ICI — the
  ``RRef.to_here()`` hop (`:110-114`) with the copy fused into the program;
* heterogeneous stages are dispatched with ``lax.switch`` on the device's
  stage index over *flattened, padded* activation buffers (SPMD programs need
  uniform shapes; padding to the widest stage boundary is the TPU-native
  encoding of "different tensors per worker");
* ``jax.grad`` differentiates through scan+ppermute+switch, so backward
  needs no distributed-autograd engine: the transpose of ppermute IS the
  reverse hop (SURVEY.md §2.2 "mechanism dissolved");
* gradients are ``psum``'d over the stage axis (each device produced only its
  own stage's grads) and ``pmean``'d over the data axis, then one optax
  update runs identically everywhere — no DistributedOptimizer RPCs
  (`:202-206`).

The reference serializes micro-batches *within* a stage with a
``threading.Lock`` (`:48,112,137`); here a stage processes one micro-batch
per tick by construction and stages are pure, so the hazard doesn't exist.

:func:`make_pipeline_train_step` replicates every stage's params across the
mesh (memory cost ``n_stages ×``; fine for tiny pipelines).  For the
memory-scaled variants use :func:`make_packed_pipeline_train_step`
(heterogeneous stages, params packed into a stage-sharded buffer — each
device holds ≈ the widest stage instead of the sum) or, for deep
homogeneous stacks, :func:`make_stacked_pipeline_train_step` (stacked
pytree sharded over the stage axis, no switch).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Mapping, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.common import jit_sharded_step

# A stage is a pure function (stage_params, activations[mb, ...]) -> out[mb, ...]
StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def _numel(shape: Sequence[int]) -> int:
    return math.prod(shape[1:])


def _flatten_pad(a: jnp.ndarray, width: int, dtype) -> jnp.ndarray:
    flat = a.reshape(a.shape[0], -1).astype(dtype)
    return jnp.pad(flat, ((0, 0), (0, width - flat.shape[1])))


def _boundary_shapes(stage_fns, params, x_mb_shape, x_dtype):
    """Static shape chain: input of each stage + final output (eval_shape —
    no FLOPs, trace-time only)."""
    shapes = [jax.ShapeDtypeStruct(x_mb_shape, x_dtype)]
    for s, fn in enumerate(stage_fns):
        shapes.append(jax.eval_shape(fn, params[s], shapes[-1]))
    return shapes


def _check_microbatchable(b: int, num_microbatches: int) -> None:
    if b % num_microbatches:
        raise ValueError(
            f"local batch {b} not divisible by {num_microbatches} micro-batches"
        )


def _run_schedule(
    apply_buf,          # (buf, t) -> buf' : one stage application on buffers
    encode,             # micro-batch [mb, ...] -> buffer
    decode,             # buffer -> output micro-batch
    xs: jnp.ndarray,    # [S, mb, ...] local micro-batches
    buf0: jnp.ndarray,
    out0: jnp.ndarray,  # [S, *out_shape] accumulator
    *,
    n_stages: int,
    stage_axis: str,
):
    """The GPipe fill-drain schedule, shared by both pipeline variants:
    stage 0 ingests micro-batch ``t`` at tick ``t``; the last stage drains
    micro-batch ``t - (n_stages - 1)``; activations hop one stage per tick
    via ``ppermute``.  Returns the [S, ...] outputs, nonzero only on the
    last stage's devices."""
    S = xs.shape[0]
    my_stage = lax.axis_index(stage_axis)

    def tick(carry, t):
        buf, outputs = carry
        x_t = encode(xs[jnp.clip(t, 0, S - 1)])
        buf_in = jnp.where(my_stage == 0, x_t, buf)
        y = apply_buf(buf_in, t)
        m = t - (n_stages - 1)
        m_clip = jnp.clip(m, 0, S - 1)
        valid = (my_stage == n_stages - 1) & (m >= 0)
        current = lax.dynamic_slice_in_dim(outputs, m_clip, 1, axis=0)[0]
        outputs = lax.dynamic_update_slice_in_dim(
            outputs, jnp.where(valid, decode(y), current)[None], m_clip, axis=0
        )
        if n_stages > 1:
            buf = lax.ppermute(
                y, stage_axis, [(i, i + 1) for i in range(n_stages - 1)]
            )
        else:
            buf = y
        return (buf, outputs), None

    (_, outputs), _ = lax.scan(tick, (buf0, out0), jnp.arange(S + n_stages - 1))
    return outputs


def _pipeline_forward(
    stage_fns,
    params,
    xs: jnp.ndarray,
    *,
    stage_axis: str,
    n_stages: int,
    remat: bool,
    buf_dtype=jnp.float32,
):
    """Heterogeneous-stage forward on the local shard: stages dispatched by
    ``lax.switch`` over flattened activation buffers padded to the widest
    stage boundary.  ``xs``: [S, mb, ...] local micro-batches."""
    S, mb = xs.shape[0], xs.shape[1]
    shapes = _boundary_shapes(stage_fns, params, (mb, *xs.shape[2:]), xs.dtype)
    for s in shapes:
        if not jnp.issubdtype(s.dtype, jnp.floating):
            raise ValueError(
                f"stage-boundary dtype {s.dtype} cannot round-trip through the "
                f"{jnp.dtype(buf_dtype).name} pipeline buffer; move integer "
                "inputs (e.g. token-id embedding) inside stage 0"
            )
    width = max(_numel(s.shape) for s in shapes)
    out_struct = shapes[-1]
    out_numel = _numel(out_struct.shape)

    def make_branch(s: int):
        def run(operand):
            branch_params, buf = operand
            xin = (
                buf[:, : _numel(shapes[s].shape)]
                .reshape(mb, *shapes[s].shape[1:])
                .astype(shapes[s].dtype)
            )
            out = stage_fns[s](branch_params[s], xin)
            return _flatten_pad(out, width, buf_dtype)

        return jax.checkpoint(run) if remat else run

    branches = [make_branch(s) for s in range(n_stages)]
    my_stage = lax.axis_index(stage_axis)

    return _run_schedule(
        apply_buf=lambda buf, t: lax.switch(my_stage, branches, (params, buf)),
        encode=lambda a: _flatten_pad(a, width, buf_dtype),
        decode=lambda y: (
            y[:, :out_numel].reshape(out_struct.shape).astype(out_struct.dtype)
        ),
        xs=xs,
        buf0=jnp.zeros((mb, width), buf_dtype),
        out0=jnp.zeros((S, *out_struct.shape), out_struct.dtype),
        n_stages=n_stages,
        stage_axis=stage_axis,
    )


def make_pipeline_train_step(
    stage_fns: Sequence[StageFn],
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    num_microbatches: int,
    data_axis: str = "data",
    stage_axis: str = "stage",
    remat: bool = False,
    donate: bool = True,
    buf_dtype=jnp.float32,
):
    """Build ``train_step(state, x, y) -> (state, metrics)``.

    ``state.params`` must be a tuple/list with one entry per stage (as built
    by e.g. ``tpudist.models.resnet50_stages`` + per-stage ``init``), fully
    replicated over the mesh.  ``x``/``y`` are global batches sharded along
    ``data_axis``; the local batch is split into ``num_microbatches``
    contiguous micro-batches exactly like the reference's
    ``xs.split(split_size)`` (`model_parallel_ResNet50.py:169`;
    ``num_microbatches`` ≙ its ``num_split`` sweep values {4, 8}).

    With ``donate=True`` (default) the input state is CONSUMED by each call —
    including any caller-held arrays that alias it (e.g. the params tree the
    state was created from, if it was already on device).  To run several
    independent sweeps from one initialization, pass ``donate=False`` or
    rebuild the state per sweep.
    """
    n_stages = mesh.shape[stage_axis]
    if len(stage_fns) != n_stages:
        raise ValueError(
            f"{len(stage_fns)} stage fns but mesh {stage_axis}={n_stages}"
        )

    def _step(state, batch):
        x, y = batch
        b = x.shape[0]
        _check_microbatchable(b, num_microbatches)
        xs = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])

        def local_loss(params):
            outputs = _pipeline_forward(
                stage_fns, params, xs,
                stage_axis=stage_axis, n_stages=n_stages, remat=remat,
                buf_dtype=buf_dtype,
            )
            # The loss lives ONLY on the last stage (outputs are zeros
            # elsewhere).  Keeping it masked-local — no collective in the
            # differentiated path — means backward cotangents flow to earlier
            # stages exclusively through the transposed ppermute hops, which
            # is exactly the reverse pipeline schedule.
            l = loss_fn(outputs.reshape(b, *outputs.shape[2:]), y)
            return jnp.where(lax.axis_index(stage_axis) == n_stages - 1, l, 0.0)

        loss, grads = jax.value_and_grad(local_loss)(state.params)
        # each device holds grads only for its own stage → assemble over the
        # stage axis, then average over data shards (the DDP-style sync)
        grads = lax.psum(grads, stage_axis)
        grads = lax.pmean(grads, data_axis)
        metrics = {"loss": lax.pmean(lax.psum(loss, stage_axis), data_axis)}
        return state.apply_gradients(grads), metrics

    stepped = jit_sharded_step(
        _step, mesh, (P(), (P(data_axis), P(data_axis))), (P(), P()), donate
    )

    def train_step(state, x, y):
        return stepped(state, (x, y))

    train_step.lower = lambda state, x, y: stepped.lower(state, (x, y))
    train_step.jitted = stepped
    return train_step


def make_pipeline_forward(
    stage_fns: Sequence[StageFn],
    mesh: Mesh,
    num_microbatches: int,
    data_axis: str = "data",
    stage_axis: str = "stage",
    buf_dtype=jnp.float32,
):
    """Inference-only pipelined forward: ``fn(params, x) -> logits``."""
    n_stages = mesh.shape[stage_axis]

    def _fwd(params, x):
        b = x.shape[0]
        _check_microbatchable(b, num_microbatches)
        xs = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
        outputs = _pipeline_forward(
            stage_fns, params, xs,
            stage_axis=stage_axis, n_stages=n_stages, remat=False,
            buf_dtype=buf_dtype,
        )
        outputs = lax.psum(outputs, stage_axis)
        return outputs.reshape(b, *outputs.shape[2:])

    return jit_sharded_step(
        _fwd, mesh, (P(), P(data_axis)), P(data_axis), donate_first=False
    )


def stacked_state_specs(state, n_stages: int, stage_axis: str = "stage",
                        overrides: Mapping[str, P] | None = None):
    """PartitionSpec pytree for a TrainState whose params are stage-stacked:
    every array leaf with leading dim ``n_stages`` shards over the stage
    axis (params and the mirroring optimizer moments), everything else
    (step counters, scalars, rng) replicates.

    The leading-dim test is a HEURISTIC: a leaf whose first dim happens to
    equal ``n_stages`` without being stacked (a ``[P, P]`` router table, a
    vocab of exactly ``n_stages``) would silently mis-shard.  ``overrides``
    escapes it: ``{path_substring: spec}`` pins the spec of every leaf whose
    ``jax.tree_util.keystr`` path contains the substring (first match wins),
    bypassing the shape guess entirely for those leaves."""

    def leaf_spec(path, leaf):
        if overrides:
            name = jax.tree_util.keystr(path)
            for pat, spec in overrides.items():
                if pat in name:
                    return spec
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == n_stages:
            return P(stage_axis)
        return P()

    return jax.tree_util.tree_map_with_path(leaf_spec, state)


def state_specs_like(state, param_specs,
                     mirrors: Mapping[str, bool] | None = None):
    """Full-TrainState spec tree from a params spec tree: every opt-state
    subtree that mirrors the params — same pytree structure AND same
    per-leaf shapes (Adam moments etc.) — gets ``param_specs``;
    everything else (counts, scalars) replicates.

    Matching by structure + shape rather than shape alone means two param
    leaves sharing a shape but needing different specs can never
    cross-contaminate each other's optimizer moments (same motivation as
    `ps_state_specs`' match-by-path, adapted to optax's mirrored trees);
    the shape condition also keeps scalar state (e.g. Adam's count) from
    matching when ``params`` is a single bare array.

    A subtree with the params' STRUCTURE but different leaf shapes is
    ambiguous — the old behaviour replicated it silently, which mis-shards
    any transform that stores param-aligned-but-reshaped state (factored
    second moments, quantized moments).  Such subtrees now raise, naming
    the offending path; resolve with ``mirrors``: ``{path_substring: bool}``
    matched against the subtree's ``keystr`` path within ``opt_state``
    (``True`` → treat as param-mirroring and apply ``param_specs``,
    ``False`` → replicate every leaf).  ``mirrors`` also overrides the
    heuristic where it *would* have matched."""
    param_treedef = jax.tree.structure(state.params)
    param_shapes = [getattr(l, "shape", None)
                    for l in jax.tree.leaves(state.params)]
    bare = param_treedef == jax.tree.structure(0)  # single-array params

    def struct_matches(subtree) -> bool:
        if bare:
            # every leaf "matches" a bare-array structure; fall back to the
            # shape test so scalars (Adam's count) keep replicating
            return (getattr(subtree, "shape", None) == param_shapes[0]
                    and not isinstance(subtree, P))
        return jax.tree.structure(subtree) == param_treedef

    def decide(path, sub):
        name = jax.tree_util.keystr(path)
        if mirrors is not None:
            for pat, flag in mirrors.items():
                if pat in name:
                    return (param_specs if flag
                            else jax.tree.map(lambda _: P(), sub))
        if not struct_matches(sub):
            return P()  # a plain non-mirroring leaf
        shapes = [getattr(l, "shape", None) for l in jax.tree.leaves(sub)]
        if shapes == param_shapes:
            return param_specs
        bad = next((i for i, (s, p) in enumerate(zip(shapes, param_shapes))
                    if s != p), 0)
        raise ValueError(
            f"optimizer-state subtree {name} has the params' tree structure "
            f"but different leaf shapes (leaf {bad}: {shapes[bad]} vs param "
            f"{param_shapes[bad]}); guessing would silently mis-shard it — "
            f"pass mirrors={{{name!r}: True}} to apply the param specs "
            f"anyway, or mirrors={{{name!r}: False}} to replicate it")

    opt_specs = jax.tree_util.tree_map_with_path(
        decide, state.opt_state, is_leaf=struct_matches)
    return state.replace(
        params=param_specs, opt_state=opt_specs, step=P(), rng=P())


def make_stacked_pipeline_train_step(
    block_fn: StageFn,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    num_microbatches: int,
    state_example,
    data_axis: str = "data",
    stage_axis: str = "stage",
    remat: bool = False,
    donate: bool = True,
    state_specs=None,
    grad_sync_axes: Sequence[str] | Any | None = None,
):
    """Pipeline of HOMOGENEOUS blocks with stage-sharded parameters.

    ``state.params`` leaves are stacked ``[n_stages, ...]`` and sharded
    ``P(stage_axis)`` — each device holds only its own stage's slice, so
    parameter memory scales O(1/n_stages) (the property that makes pipeline
    parallelism worth having at scale; the reference's two-shard placement
    `model_parallel_ResNet50.py:152-165` achieves the same by construction).
    The block must map activations to activations of the same shape.

    ``state_example`` (a TrainState, concrete or abstract) is used only to
    derive the per-leaf sharding specs via :func:`stacked_state_specs`.

    ``state_specs`` overrides the derived specs — the hook for 3-D
    DP×PP×TP runs: shard param leaves over a ``model`` axis too and make
    ``block_fn`` a tensor-parallel block built from the AD-correct
    collectives in :mod:`tpudist.parallel.common`
    (``id_fwd_psum_bwd`` / ``psum_fwd_id_bwd``).  Gradients for every
    leaf SHARDED over a tensor axis stay local to its shard; a leaf left
    REPLICATED over a tensor axis (e.g. a layernorm scale inside a TP
    block) receives per-shard PARTIAL gradients — Megatron cotangents
    between the f/g collectives are partial sums — so its grads are
    ``psum``'d over every ``grad_sync_axes`` axis missing from its spec.
    ``grad_sync_axes`` is REQUIRED (explicitly, possibly ``()``) whenever
    ``state_specs`` is given and the mesh has axes beyond ``data_axis`` /
    ``stage_axis`` — the sync is an opt-in, because for leaves whose
    gradient is already complete it would silently scale by the axis size.

    The psum is only correct for replicated leaves whose cotangents are
    per-shard partials (used strictly between the f/g collectives); a
    replicated leaf used OUTSIDE that region (e.g. a bias added after
    ``psum_fwd_id_bwd``, the standard row-parallel bias position) already
    has the COMPLETE gradient on every shard, and a psum would scale it by
    the axis size.  For blocks mixing both kinds, pass ``grad_sync_axes``
    as a PYTREE matching ``params``: each leaf a tuple of axis names to
    sync for that leaf (``()`` for already-complete leaves).  A flat
    sequence applies the same axes to every leaf; ``()`` disables the sync
    entirely (every extra mesh axis unused inside ``block_fn``).
    """
    n_stages = mesh.shape[stage_axis]
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_example.params):
        if not (hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == n_stages):
            raise ValueError(
                f"stacked pipeline requires every param leaf stacked "
                f"[{n_stages}, ...]; {jax.tree_util.keystr(path)} has shape "
                f"{getattr(leaf, 'shape', None)}"
            )
    if state_specs is None:
        state_specs = stacked_state_specs(state_example, n_stages, stage_axis)
        if grad_sync_axes is None:
            grad_sync_axes = ()
    else:
        # The schedule indexes the LOCAL stage slice (`p[0]`); a param spec
        # that doesn't shard dim 0 over the stage axis would silently run
        # every stage on chunk 0's weights.
        for path, spec in jax.tree_util.tree_leaves_with_path(
                state_specs.params,
                is_leaf=lambda x: isinstance(x, P)):
            if not (isinstance(spec, P) and len(spec) >= 1
                    and spec[0] == stage_axis):
                raise ValueError(
                    f"state_specs param leaf {jax.tree_util.keystr(path)} "
                    f"must shard its leading (stage) dim over "
                    f"{stage_axis!r}; got {spec}")
        if grad_sync_axes is None:
            # Explicit opt-in (ADVICE r2): inferring "every extra mesh axis"
            # silently psums already-complete gradients (the row-parallel-
            # bias case) and scales them by the axis size.  The caller
            # knows which leaves carry partial cotangents; we don't.
            extra = tuple(a for a in mesh.axis_names
                          if a not in (data_axis, stage_axis))
            if not extra:
                grad_sync_axes = ()
            else:
                raise ValueError(
                    f"state_specs given with extra mesh axes {extra}: pass "
                    f"grad_sync_axes explicitly — grad_sync_axes={extra} "
                    f"to psum partial-cotangent replicated leaves over "
                    f"them, grad_sync_axes=() if every replicated leaf's "
                    f"gradient is already complete, or a per-leaf pytree "
                    f"for mixed blocks (see docstring)")
    # Per-leaf static plan: which sync axes each param leaf's spec leaves
    # it replicated over (its grads there are per-shard partials that the
    # data-axis mean alone would silently desync — see docstring).
    spec_leaves = jax.tree.leaves(
        state_specs.params, is_leaf=lambda x: isinstance(x, P))
    if isinstance(grad_sync_axes, (tuple, list)):
        sync_per_leaf = [tuple(grad_sync_axes)] * len(spec_leaves)
    else:  # pytree matching params: per-leaf axis tuples
        sync_per_leaf = [
            tuple(s) for s in jax.tree.leaves(
                grad_sync_axes,
                is_leaf=lambda x: isinstance(x, (tuple, list)))]
        if len(sync_per_leaf) != len(spec_leaves):
            raise ValueError(
                f"grad_sync_axes pytree has {len(sync_per_leaf)} leaves "
                f"but params have {len(spec_leaves)}")
    missing_per_leaf = [
        tuple(a for a in sync if a not in _spec_axes(s))
        for sync, s in zip(sync_per_leaf, spec_leaves)]

    def _step(state, batch):
        x, y = batch
        b = x.shape[0]
        _check_microbatchable(b, num_microbatches)
        xs = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
        my_stage = lax.axis_index(stage_axis)

        def local_loss(params):
            # local param slice has leading dim 1 from the stage sharding
            my_params = jax.tree.map(lambda p: p[0], params)
            run = jax.checkpoint(block_fn) if remat else block_fn
            outputs = _run_schedule(
                apply_buf=lambda buf, t: run(my_params, buf),
                encode=lambda a: a,
                decode=lambda yv: yv,
                xs=xs,
                buf0=jnp.zeros_like(xs[0]),
                out0=jnp.zeros_like(xs),
                n_stages=n_stages,
                stage_axis=stage_axis,
            )
            # masked-local loss on the last stage; cotangents reach earlier
            # stages through the transposed ppermute (see the heterogeneous
            # variant for rationale)
            l = loss_fn(outputs.reshape(b, *outputs.shape[2:]), y)
            return jnp.where(my_stage == n_stages - 1, l, 0.0)

        loss, grads = jax.value_and_grad(local_loss)(state.params)
        # stage-sharded params: each device's grads are for its own slice
        # already — only the data-axis average is needed, plus (3-D) the
        # tensor-axis psum for any leaf replicated over a sync axis.
        grads = lax.pmean(grads, data_axis)
        if any(missing_per_leaf):
            leaves, treedef = jax.tree.flatten(grads)
            leaves = [lax.psum(g, m) if m else g
                      for g, m in zip(leaves, missing_per_leaf)]
            grads = jax.tree.unflatten(treedef, leaves)
        metrics = {"loss": lax.pmean(lax.psum(loss, stage_axis), data_axis)}
        return state.apply_gradients(grads), metrics

    stepped = jit_sharded_step(
        _step, mesh, (state_specs, (P(data_axis), P(data_axis))),
        (state_specs, P()), donate,
    )

    def train_step(state, x, y):
        return stepped(state, (x, y))

    train_step.lower = lambda state, x, y: stepped.lower(state, (x, y))
    train_step.jitted = stepped
    # GPipe fill-drain: (P-1) idle ticks at each end of the 2(M+P-1) span
    train_step.bubble_fraction = (n_stages - 1) / (
        num_microbatches + n_stages - 1)
    return train_step


def _spec_axes(spec) -> set:
    """Mesh axes a PartitionSpec shards over (flattening tuple entries)."""
    axes: set = set()
    for part in spec:
        if part is None:
            continue
        axes.update(part if isinstance(part, tuple) else (part,))
    return axes


# --------------------------------------------------------------------------
# Stage-sharded heterogeneous pipeline (packed parameters)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class StagePacking:
    """Static metadata mapping heterogeneous per-stage param trees onto one
    ``[n_stages, width]`` buffer shardable over the stage axis."""

    treedefs: tuple
    shapes: tuple    # per stage: tuple of leaf shapes
    dtypes: tuple    # per stage: tuple of leaf dtypes
    width: int       # padded flat width = widest stage's element count

    @property
    def n_stages(self) -> int:
        return len(self.treedefs)


def pack_stage_params(stage_params: Sequence[Any], buf_dtype=jnp.float32):
    """Flatten heterogeneous per-stage param trees into a single
    ``[n_stages, width]`` array (each stage's leaves raveled, concatenated,
    zero-padded to the widest stage) plus the :class:`StagePacking` needed
    to invert it.  The packed array shards ``P(stage_axis)`` — each device
    then holds only its own stage's parameters, the property the
    reference's two-shard placement has by construction
    (`model_parallel_ResNet50.py:152-165`)."""
    treedefs, shapes, dtypes, vecs = [], [], [], []
    for tree in stage_params:
        leaves, td = jax.tree.flatten(tree)
        for leaf in leaves:
            if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                raise ValueError(
                    f"packed pipeline params must be floating to share one "
                    f"buffer; got {jnp.asarray(leaf).dtype}")
        treedefs.append(td)
        shapes.append(tuple(tuple(np.shape(leaf)) for leaf in leaves))
        dtypes.append(tuple(jnp.asarray(leaf).dtype for leaf in leaves))
        vecs.append(
            jnp.concatenate(
                [jnp.ravel(jnp.asarray(leaf)).astype(buf_dtype)
                 for leaf in leaves])
            if leaves else jnp.zeros((0,), buf_dtype))
    width = max(v.shape[0] for v in vecs)
    flat = jnp.stack([jnp.pad(v, (0, width - v.shape[0])) for v in vecs])
    return flat, StagePacking(
        tuple(treedefs), tuple(shapes), tuple(dtypes), width)


def unpack_stage(vec: jnp.ndarray, meta: StagePacking, s: int):
    """One stage's param tree from its flat ``[width]`` slice (static
    shapes/dtypes; differentiable — the transpose re-ravels grads)."""
    leaves, off = [], 0
    for shape, dt in zip(meta.shapes[s], meta.dtypes[s]):
        n = math.prod(shape)
        leaves.append(lax.dynamic_slice_in_dim(vec, off, n)
                      .reshape(shape).astype(dt))
        off += n
    return jax.tree_util.tree_unflatten(meta.treedefs[s], leaves)


def unpack_stage_params(flat, meta: StagePacking):
    """Invert :func:`pack_stage_params` (host-side, for checkpoint export)."""
    return tuple(
        unpack_stage(flat[s], meta, s) for s in range(meta.n_stages))


def make_packed_pipeline_train_step(
    stage_fns: Sequence[StageFn],
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    num_microbatches: int,
    meta: StagePacking,
    state_example,
    data_axis: str = "data",
    stage_axis: str = "stage",
    remat: bool = False,
    donate: bool = True,
    buf_dtype=jnp.float32,
):
    """Heterogeneous pipeline with STAGE-SHARDED parameters.

    Same schedule and numerics as :func:`make_pipeline_train_step`, but
    ``state.params`` is the packed ``[n_stages, width]`` buffer from
    :func:`pack_stage_params` sharded ``P(stage_axis)``: per-device
    parameter (and optimizer-moment) memory is ``width`` — the widest
    stage — instead of the sum over stages, restoring the O(1/P) memory
    scaling that makes pipeline parallelism worth having (the round-1 gap:
    `pipeline.py` replicated every stage everywhere).  Inside the step each
    device unpacks ONLY its own slice; ``lax.switch`` picks the stage
    branch, which reinterprets the flat slice with that stage's static
    shapes.

    The optimizer runs elementwise on the packed buffer — identical to
    per-leaf updates for elementwise transforms (sgd / adam family);
    padding entries get zero gradients and never move.  Transforms that
    couple leaves (global-norm clipping) see zero-padded concatenation,
    which preserves norms.
    """
    n_stages = mesh.shape[stage_axis]
    if meta.n_stages != n_stages or len(stage_fns) != n_stages:
        raise ValueError(
            f"{len(stage_fns)} stage fns / {meta.n_stages} packed stages "
            f"but mesh {stage_axis}={n_stages}")
    state_specs = stacked_state_specs(state_example, n_stages, stage_axis)

    # static activation-boundary chain via abstract per-stage params
    param_structs = [
        jax.tree_util.tree_unflatten(
            meta.treedefs[s],
            [jax.ShapeDtypeStruct(sh, dt)
             for sh, dt in zip(meta.shapes[s], meta.dtypes[s])])
        for s in range(n_stages)
    ]

    def _step(state, batch):
        x, y = batch
        b = x.shape[0]
        _check_microbatchable(b, num_microbatches)
        mb = b // num_microbatches
        xs = x.reshape(num_microbatches, mb, *x.shape[1:])
        shapes = _boundary_shapes(
            stage_fns, param_structs, (mb, *x.shape[1:]), x.dtype)
        for sds in shapes:
            if not jnp.issubdtype(sds.dtype, jnp.floating):
                raise ValueError(
                    f"stage-boundary dtype {sds.dtype} cannot round-trip "
                    f"through the {jnp.dtype(buf_dtype).name} pipeline "
                    "buffer; move integer inputs inside stage 0")
        act_width = max(_numel(sds.shape) for sds in shapes)
        out_struct = shapes[-1]
        out_numel = _numel(out_struct.shape)
        my_stage = lax.axis_index(stage_axis)

        def local_loss(packed):
            p_vec = packed[0]  # this device's stage slice, [width]

            def make_branch(s: int):
                def run(operand):
                    vec, buf = operand
                    tree = unpack_stage(vec, meta, s)
                    xin = (
                        buf[:, : _numel(shapes[s].shape)]
                        .reshape(mb, *shapes[s].shape[1:])
                        .astype(shapes[s].dtype)
                    )
                    out = stage_fns[s](tree, xin)
                    return _flatten_pad(out, act_width, buf_dtype)

                return jax.checkpoint(run) if remat else run

            branches = [make_branch(s) for s in range(n_stages)]
            outputs = _run_schedule(
                apply_buf=lambda buf, t: lax.switch(
                    my_stage, branches, (p_vec, buf)),
                encode=lambda a: _flatten_pad(a, act_width, buf_dtype),
                decode=lambda yv: (
                    yv[:, :out_numel].reshape(out_struct.shape)
                    .astype(out_struct.dtype)),
                xs=xs,
                buf0=jnp.zeros((mb, act_width), buf_dtype),
                out0=jnp.zeros((num_microbatches, *out_struct.shape),
                               out_struct.dtype),
                n_stages=n_stages,
                stage_axis=stage_axis,
            )
            l = loss_fn(outputs.reshape(b, *outputs.shape[2:]), y)
            return jnp.where(my_stage == n_stages - 1, l, 0.0)

        loss, grads = jax.value_and_grad(local_loss)(state.params)
        # packed params are stage-sharded: grads are slice-local already
        grads = lax.pmean(grads, data_axis)
        metrics = {"loss": lax.pmean(lax.psum(loss, stage_axis), data_axis)}
        return state.apply_gradients(grads), metrics

    stepped = jit_sharded_step(
        _step, mesh, (state_specs, (P(data_axis), P(data_axis))),
        (state_specs, P()), donate,
    )

    def train_step(state, x, y):
        return stepped(state, (x, y))

    train_step.lower = lambda state, x, y: stepped.lower(state, (x, y))
    train_step.jitted = stepped
    return train_step


# --------------------------------------------------------------------------
# 1F1B pipeline schedule
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _OneFOneBSchedule:
    """Static 1F1B schedule tables, all [T, P] int32 (-1 = nothing).

    kind   -1 idle / 0 forward / 1 backward
    m      micro-batch executed this tick
    v      local virtual-chunk index executed this tick (0 when V == 1)
    frecv  act-buffer slot banking the activation arriving at tick start
    crecv  cot-buffer slot banking the cotangent arriving at tick start
    fread  act-buffer slot holding the executed micro-batch's INPUT
           activation (-1: read from xs — chunk 0); kept across fwd,
           freed at bwd (the recompute source)
    cread  cot-buffer slot holding the incoming cotangent for a backward
           tick (-1: the LAST chunk seeds from the loss)
    Qa/Qc  act/cot buffer sizes — Qa is THE 1F1B memory story: bounded by
           the in-flight cap (~P·V at the first device), not by M·V as in
           GPipe
    """

    T: int
    Qa: int
    Qc: int
    kind: np.ndarray
    m: np.ndarray
    v: np.ndarray
    frecv: np.ndarray
    crecv: np.ndarray
    fread: np.ndarray
    cread: np.ndarray


def _canonical_interleaved_order(P: int, V: int,
                                 M: int) -> list[list[tuple]]:
    """The canonical Megatron-LM interleaved-1F1B op order, per device:
    ``[(kind, m, v), ...]`` with kind 0=forward, 1=backward.

    Device p runs ``W(p) = min(2(P-p-1) + (V-1)P, MV)`` warmup forwards,
    then strict 1F1B alternation, then the backward cooldown.  The k-th
    forward (0-indexed) executes micro-batch ``(k // PV)·P + k % P`` on
    local chunk ``(k % PV) // P`` — micro-batches advance in GROUPS OF P
    per chunk, which is what keeps the steady-state ring full and the
    bubble at ~1/V of plain 1F1B's (the greedy deepest-chunk-first
    priority degrades to WORSE than plain at M >> P: measured 161 vs 156
    chunk-ticks at P=8/M=32/V=2, vs 142 canonical).  Requires
    ``M % P == 0`` (the Megatron interleaving condition)."""
    total = M * V

    def mb(k: int, forward: bool) -> tuple[int, int]:
        v = (k % (P * V)) // P
        if not forward:
            v = V - 1 - v
        return (k // (P * V)) * P + k % P, v

    ops: list[list[tuple]] = []
    for p in range(P):
        warmup = min((P - p - 1) * 2 + (V - 1) * P, total)
        seq: list[tuple] = []
        fk = bk = 0
        for _ in range(warmup):
            seq.append((0, *mb(fk, True)))
            fk += 1
        while fk < total:
            seq.append((0, *mb(fk, True)))
            fk += 1
            seq.append((1, *mb(bk, False)))
            bk += 1
        while bk < total:
            seq.append((1, *mb(bk, False)))
            bk += 1
        ops.append(seq)
    return ops


def _one_f_one_b_schedule(P: int, M: int, V: int = 1) -> _OneFOneBSchedule:
    """Event-driven simulation of the 1F1B schedule, plain (V=1) or
    interleaved (V>1 — the Megatron-LM schedule: chunk ``c = v·P + p``
    lives on device ``c mod P``; micro-batches loop the ring V times in
    forward and V times in reverse for backward).

    V>1 with ``M % P == 0`` follows the CANONICAL per-device op order
    (:func:`_canonical_interleaved_order`), executed earliest-start:
    each device runs its next op the first tick its input has arrived —
    measured strictly better than plain 1F1B at every tested (P, M, V).
    Other configurations fall back to greedy priorities: a device
    prefers backward work (oldest micro-batch, deepest chunk first);
    otherwise it forwards (deepest ready chunk first) while its
    in-flight count stays under its cap — ``P - p`` at V=1 (the
    canonical plain-1F1B warmup), Megatron's warmup bound
    ``2(P-p-1) + (V-1)P + 1`` at V>1.

    Transport either way: a forward output hops one device down the ring
    and a cotangent one device up, landing at the next tick's start; the
    LAST chunk's backward self-unlocks one tick after its forward
    (loss-seeded, nothing travels)."""
    L = P * V
    order = (_canonical_interleaved_order(P, V, M)
             if V > 1 and M % P == 0 else None)
    ptr = [0] * P
    if V == 1:
        caps = [P - p for p in range(P)]
    else:
        caps = [2 * (P - p - 1) + (V - 1) * P + 1 for p in range(P)]
    act_slot: list[dict] = [dict() for _ in range(P)]   # (m, v) -> slot
    cot_slot: list[dict] = [dict() for _ in range(P)]
    free_a: list[list[int]] = [[] for _ in range(P)]
    free_c: list[list[int]] = [[] for _ in range(P)]
    next_a = [0] * P
    next_c = [0] * P
    fwd_ready: list[set] = [set() for _ in range(P)]    # {(m, v)}
    bwd_ready: list[set] = [set() for _ in range(P)]
    arriving_f: list[tuple | None] = [None] * P
    arriving_c: list[tuple | None] = [None] * P
    self_ready: dict[int, int] = {}  # last chunk: m -> unlock tick
    next_launch = 0  # chunk 0 (device 0, v 0) feeds micro-batches in order
    in_flight = [0] * P
    bwd_done = [0] * P
    cols: dict[str, list] = {k: [] for k in
                             ("kind", "m", "v", "frecv", "crecv",
                              "fread", "cread")}

    def alloc(free: list[int], nxt: list[int], p: int) -> int:
        if free[p]:
            return free[p].pop()
        nxt[p] += 1
        return nxt[p] - 1

    def do_backward(p: int, m: int, v: int, row: dict,
                    nc: list) -> None:
        row["kind"][p], row["m"][p], row["v"][p] = 1, m, v
        if (m, v) in act_slot[p]:
            s = act_slot[p].pop((m, v))
            row["fread"][p] = s
            free_a[p].append(s)
        if (m, v) in cot_slot[p]:
            s = cot_slot[p].pop((m, v))
            row["cread"][p] = s
            free_c[p].append(s)
        in_flight[p] -= 1
        bwd_done[p] += 1
        if v * P + p > 0:  # cotangent to chunk c-1 (one device up the ring)
            nc[(p - 1) % P] = (m, v - 1 if p == 0 else v)

    def do_forward(p: int, m: int, v: int, row: dict, nf: list,
                   t: int) -> None:
        row["kind"][p], row["m"][p], row["v"][p] = 0, m, v
        row["fread"][p] = act_slot[p].get((m, v), -1)
        in_flight[p] += 1
        if v * P + p < L - 1:  # activation to chunk c+1 (one device down)
            nf[(p + 1) % P] = (m, v + 1 if p == P - 1 else v)
        else:
            self_ready[m] = t + 1

    t = 0
    while any(d < M * V for d in bwd_done):
        row = {k: [-1] * P for k in cols}
        # 1. arrivals land
        nf, nc = [None] * P, [None] * P
        for p in range(P):
            if arriving_f[p] is not None:
                mv = arriving_f[p]
                s = alloc(free_a, next_a, p)
                act_slot[p][mv] = s
                row["frecv"][p] = s
                fwd_ready[p].add(mv)
            if arriving_c[p] is not None:
                mv = arriving_c[p]
                s = alloc(free_c, next_c, p)
                cot_slot[p][mv] = s
                row["crecv"][p] = s
                bwd_ready[p].add(mv)
        for m, tick in list(self_ready.items()):
            if tick <= t:
                bwd_ready[P - 1].add((m, V - 1))
                del self_ready[m]
        # 2. execution
        for p in range(P):
            if order is not None:
                # canonical mode: run this device's NEXT op the first
                # tick its input is present; never reorder
                if ptr[p] >= len(order[p]):
                    continue
                kind, m, v = order[p][ptr[p]]
                if kind == 1:
                    if (m, v) not in bwd_ready[p]:
                        continue
                    bwd_ready[p].discard((m, v))
                    do_backward(p, m, v, row, nc)
                else:
                    launch = p == 0 and v == 0
                    if not launch and (m, v) not in fwd_ready[p]:
                        continue
                    if launch:
                        next_launch += 1
                    else:
                        fwd_ready[p].discard((m, v))
                    do_forward(p, m, v, row, nf, t)
                ptr[p] += 1
                continue
            # greedy mode: backward first, else forward under the cap
            if bwd_ready[p]:
                m, v = min(bwd_ready[p], key=lambda mv: (mv[0], -mv[1]))
                bwd_ready[p].discard((m, v))
                do_backward(p, m, v, row, nc)
                continue
            # chunk-0 launches appear as a virtual ready entry so the
            # deepest-chunk-first priority arbitrates launches vs deeper
            # forwards uniformly
            candidates = set(fwd_ready[p])
            if p == 0 and next_launch < M:
                candidates.add((next_launch, 0))
            if in_flight[p] >= caps[p]:
                # the cap must never strangle the cotangent SOURCE: the
                # last chunk's forward unlocks its own backward one tick
                # later, so it is exempt (otherwise all devices can sit
                # at cap waiting for cotangents only this forward creates)
                candidates = {mv for mv in candidates
                              if p == P - 1 and mv[1] == V - 1}
            if candidates:
                m, v = min(candidates, key=lambda mv: (-mv[1], mv[0]))
                if (m, v) in fwd_ready[p]:
                    fwd_ready[p].discard((m, v))
                else:
                    next_launch += 1
                do_forward(p, m, v, row, nf, t)
        arriving_f, arriving_c = nf, nc
        for k in cols:
            cols[k].append(row[k])
        t += 1
        if t > 8 * L * M + 6 * L:  # pragma: no cover - schedule bug guard
            raise RuntimeError("1F1B scheduler did not converge")
    return _OneFOneBSchedule(
        T=t, Qa=max(max(next_a), 1), Qc=max(max(next_c), 1),
        **{k: np.asarray(val, np.int32) for k, val in cols.items()},
    )


def make_1f1b_pipeline_train_step(
    block_fn: StageFn,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray] | None,
    mesh: Mesh,
    num_microbatches: int,
    state_example,
    data_axis: str = "data",
    stage_axis: str = "stage",
    donate: bool = True,
    virtual_stages: int = 1,
    embed_fn: Callable[[Any, jnp.ndarray], jnp.ndarray] | None = None,
    head_loss_fn: Callable[[Any, jnp.ndarray, jnp.ndarray], jnp.ndarray] | None = None,
):
    """1F1B pipeline of HOMOGENEOUS blocks with stage-sharded parameters.

    Same contract and numerics as :func:`make_stacked_pipeline_train_step`
    (stacked params sharded over the stage axis; the block maps activations
    to same-shaped activations; ``loss_fn`` is a mean over its batch), but
    the backward pass is SCHEDULED, not derived: each scan tick executes
    either a forward (banking its input activation) or a backward
    (``jax.vjp`` recomputed from the banked input — per-block
    rematerialization), interleaved 1F1B.  Activation memory is the
    schedule's act buffer: O(P) in-flight micro-batches per device versus
    GPipe's O(M) saved boundaries (`_OneFOneBSchedule.Qa`, asserted in
    tests) — the reason 1F1B is the production schedule at M >> P.

    ``virtual_stages > 1`` selects the INTERLEAVED 1F1B schedule (the full
    Megatron-LM schedule): chunk ``c = v·P + p`` of a ``P·V``-deep stack
    runs on device ``c mod P``, shrinking the bubble by ~V at V extra ring
    hops per micro-batch.  The stacked params leaves must then be stacked
    ``[P·V, ...]`` in DEVICE order — build chunk-ordered params and apply
    :func:`interleave_params` first.

    Cotangents ride the reverse ``ppermute`` ring one hop per tick; the
    last CHUNK seeds them from the loss (scaled 1/M so the summed
    micro-batch gradients equal the full-batch gradient).

    **Real-model mode** (``embed_fn`` / ``head_loss_fn``): a full language
    model is NOT a homogeneous block stack — it has an embedding in front
    and a norm+head+loss behind.  Passing either hook switches
    ``state.params`` to ``{"stages": stacked_tree, "extra": extra_tree}``:
    ``stages`` is the ``[P·V, ...]``-stacked block params sharded over the
    stage axis as before; ``extra`` (embedding / final-norm / head params)
    replicates.  ``embed_fn(extra, x_mb) -> acts`` maps a raw input
    micro-batch (e.g. int token ids) to the first chunk's activations —
    the activation shape/dtype is taken from its ``eval_shape``, so it may
    differ from the input's.  ``head_loss_fn(extra, out_mb, y_mb) ->
    scalar`` replaces ``loss_fn`` on the last chunk's output (norm + head
    + mean loss in one differentiable hop).  Gradients for ``extra`` flow
    through the same per-tick ``jax.vjp``: embedding cotangents appear
    only on chunk-0 backward ticks, head cotangents only on last-chunk
    backward ticks, everywhere else they are exact zeros — summed over the
    schedule, ``psum``'d over the stage axis (each device holds a partial)
    and ``pmean``'d over data like everything else.
    """
    n_p = mesh.shape[stage_axis]
    M, V = num_microbatches, virtual_stages
    L = n_p * V
    sched = _one_f_one_b_schedule(n_p, M, V)
    tbl = {k: jnp.asarray(getattr(sched, k))
           for k in ("kind", "m", "v", "frecv", "crecv", "fread", "cread")}
    extra_mode = embed_fn is not None or head_loss_fn is not None
    if extra_mode:
        try:
            stages_example = state_example.params["stages"]
            state_example.params["extra"]
        except (TypeError, KeyError):
            raise ValueError(
                "embed_fn/head_loss_fn require state.params = "
                '{"stages": stacked_tree, "extra": extra_tree}') from None
        if head_loss_fn is None and loss_fn is None:
            raise ValueError("pass head_loss_fn or loss_fn for the last chunk")
    else:
        stages_example = state_example.params
    for path, leaf in jax.tree_util.tree_leaves_with_path(stages_example):
        if not (hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[0] == L):
            raise ValueError(
                f"1F1B pipeline requires every param leaf stacked "
                f"[{L}, ...] (P·V); {jax.tree_util.keystr(path)} has shape "
                f"{getattr(leaf, 'shape', None)}")
    if extra_mode:
        param_specs = {
            "stages": jax.tree.map(lambda _: P(stage_axis),
                                   state_example.params["stages"]),
            "extra": jax.tree.map(lambda _: P(),
                                  state_example.params["extra"]),
        }
        state_specs = state_specs_like(state_example, param_specs)
    else:
        state_specs = stacked_state_specs(state_example, L, stage_axis)
    inv_m = 1.0 / M

    def _step(state, batch):
        x, y = batch
        b = x.shape[0]
        _check_microbatchable(b, M)
        xs = x.reshape(M, b // M, *x.shape[1:])
        ys = y.reshape(M, b // M, *y.shape[1:])
        my_p = lax.axis_index(stage_axis)
        cols = tuple(
            lax.dynamic_index_in_dim(tbl[k], my_p, axis=1, keepdims=False)
            for k in ("kind", "m", "v", "frecv", "crecv", "fread", "cread"))
        if extra_mode:
            my_params = state.params["stages"]  # local stack of V chunks
            extra = state.params["extra"]
            act_sds = (jax.eval_shape(embed_fn, extra, xs[0])
                       if embed_fn is not None
                       else jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype))
        else:
            my_params = state.params
            extra = {}
            act_sds = jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype)
        zero_e = jax.tree.map(jnp.zeros_like, extra)

        def chunk0_in(ex, x_m, a_banked, use_banked):
            # chunk 0 reads its input from xs (through the embedding in
            # real-model mode); deeper chunks read the banked activation.
            # The jnp.where routes cotangents exactly: the unselected
            # branch's gradient is zero, so embedding grads appear only on
            # chunk-0 backward ticks.
            a_emb = (embed_fn(ex, x_m) if embed_fn is not None
                     else x_m.astype(a_banked.dtype))
            return jnp.where(use_banked, a_banked, a_emb)

        def tick(carry, col):
            buf_f, buf_c, act_q, cot_q, gacc, eacc, lacc = carry
            kind, m, ev, frecv, crecv, fread, cread = col
            is_last = (my_p == n_p - 1) & (ev == V - 1)
            use_banked = fread >= 0
            # 1. bank arrivals
            stored_a = lax.dynamic_update_index_in_dim(
                act_q, buf_f, jnp.clip(frecv, 0), 0)
            act_q = jnp.where(frecv >= 0, stored_a, act_q)
            stored_c = lax.dynamic_update_index_in_dim(
                cot_q, buf_c, jnp.clip(crecv, 0), 0)
            cot_q = jnp.where(crecv >= 0, stored_c, cot_q)
            # 2. resolve inputs (idle ticks compute on garbage; the
            #    schedule makes every consumer discard them)
            a_banked = lax.dynamic_index_in_dim(
                act_q, jnp.clip(fread, 0), 0, keepdims=False)
            x_m = lax.dynamic_index_in_dim(
                xs, jnp.clip(m, 0), 0, keepdims=False)
            cot_in = lax.dynamic_index_in_dim(
                cot_q, jnp.clip(cread, 0), 0, keepdims=False)
            y_m = lax.dynamic_index_in_dim(
                ys, jnp.clip(m, 0), 0, keepdims=False)
            p_v = jax.tree.map(
                lambda pr: lax.dynamic_index_in_dim(
                    pr, jnp.clip(ev, 0), 0, keepdims=False),
                my_params)
            zero_g = jax.tree.map(jnp.zeros_like, p_v)
            a_zero = jnp.zeros(act_sds.shape, act_sds.dtype)

            def seed_loss(ex, out, ym):
                if head_loss_fn is not None:
                    return head_loss_fn(ex, out, ym)
                return loss_fn(out, ym)

            def idle_branch(op):
                _ex, _ab, _c, _ym = op
                return (a_zero, a_zero, zero_g, zero_e,
                        jnp.zeros((), jnp.float32))

            def fwd_branch(op):
                ex, ab, _c, _ym = op
                out = block_fn(p_v, chunk0_in(ex, x_m, ab, use_banked))
                return (out, a_zero, zero_g, zero_e,
                        jnp.zeros((), jnp.float32))

            def bwd_branch(op):
                ex, ab, c, ym = op

                def fwd_only(pp, exx, aa):
                    return block_fn(pp, chunk0_in(exx, x_m, aa, use_banked))

                out, vjp = jax.vjp(fwd_only, p_v, ex, ab)
                # the last CHUNK seeds from the loss; others use the
                # cotangent that rode the reverse ring
                l_m, vjp_l = jax.vjp(
                    lambda exx, o: seed_loss(exx, o, ym), ex, out)
                dex_loss, dout_loss = vjp_l(jnp.asarray(inv_m, l_m.dtype))
                cot_eff = jnp.where(is_last, dout_loss.astype(out.dtype),
                                    c.astype(out.dtype))
                gp, gex, ga = vjp(cot_eff)
                # head grads exist only where the loss actually seeded
                gex = jax.tree.map(
                    lambda g, dl: g + jnp.where(is_last, dl, 0.0).astype(
                        g.dtype),
                    gex, dex_loss)
                loss_contrib = jnp.where(
                    is_last, (l_m * inv_m).astype(jnp.float32), 0.0)
                return (a_zero, ga.astype(act_sds.dtype), gp, gex,
                        loss_contrib)

            send_f, send_c, gp, gex, l_c = lax.switch(
                kind + 1, [idle_branch, fwd_branch, bwd_branch],
                (extra, a_banked, cot_in, y_m))
            gacc = jax.tree.map(
                lambda acc, g: acc.at[jnp.clip(ev, 0)].add(g), gacc, gp)
            eacc = jax.tree.map(jnp.add, eacc, gex)
            lacc = lacc + l_c
            # 3. one hop each way around the (mod-P) ring; receivers
            #    without a scheduled arrival discard via frecv/crecv = -1
            if n_p > 1:
                buf_f = lax.ppermute(
                    send_f, stage_axis,
                    [(i, (i + 1) % n_p) for i in range(n_p)])
                buf_c = lax.ppermute(
                    send_c, stage_axis,
                    [(i, (i - 1) % n_p) for i in range(n_p)])
            else:
                buf_f, buf_c = send_f, send_c
            return (buf_f, buf_c, act_q, cot_q, gacc, eacc, lacc), None

        carry0 = (
            jnp.zeros(act_sds.shape, act_sds.dtype),
            jnp.zeros(act_sds.shape, act_sds.dtype),
            jnp.zeros((sched.Qa, *act_sds.shape), act_sds.dtype),
            jnp.zeros((sched.Qc, *act_sds.shape), act_sds.dtype),
            jax.tree.map(jnp.zeros_like, my_params),
            zero_e,
            jnp.zeros((), jnp.float32),
        )
        (_, _, _, _, gacc, eacc, lacc), _ = lax.scan(tick, carry0, cols)
        grads = lax.pmean(gacc, data_axis)
        if extra_mode:
            # every device accumulated only the extra-grad slices its own
            # chunks produced (embedding on the chunk-0 owner, head on the
            # last-chunk owner) — assemble over the stage ring first
            extra_grads = lax.pmean(lax.psum(eacc, stage_axis), data_axis)
            grads = {"stages": grads, "extra": extra_grads}
        metrics = {"loss": lax.pmean(lax.psum(lacc, stage_axis), data_axis)}
        return state.apply_gradients(grads), metrics

    stepped = jit_sharded_step(
        _step, mesh, (state_specs, (P(data_axis), P(data_axis))),
        (state_specs, P()), donate,
    )

    def train_step(state, x, y):
        return stepped(state, (x, y))

    train_step.lower = lambda state, x, y: stepped.lower(state, (x, y))
    train_step.jitted = stepped
    train_step.schedule = sched
    train_step.bubble_fraction = (sched.T - 2 * V * M) / sched.T
    return train_step


# --------------------------------------------------------------------------
# Interleaved (virtual-stage) pipeline
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _InterleaveSchedule:
    """Static conflict-free schedule for the interleaved pipeline.

    All tables are [T, P] int32 (T ticks, P devices); -1 means "nothing".

    exec_v    local virtual-chunk index executed this tick
    exec_m    micro-batch index executed this tick
    recv_slot queue slot storing the activation arriving at tick start
    read_slot queue slot holding the executed chunk's input (-1: from xs)
    out_m     micro-batch index whose FINAL output this tick produces
    """

    T: int
    Q: int
    exec_v: np.ndarray
    exec_m: np.ndarray
    recv_slot: np.ndarray
    read_slot: np.ndarray
    out_m: np.ndarray


def _interleave_schedule(P: int, V: int, M: int) -> _InterleaveSchedule:
    """Greedy list scheduling of the interleaved pipeline.

    Model: chunk ``c = v·P + p`` (device ``p``, local slice ``v``) may run
    micro-batch ``m`` once its input is present; outputs ``ppermute`` one
    device down the ring at tick end and arrive at the next tick's start.
    Devices pick drain-first (highest ``v``, then lowest ``m``) among ready
    work — the priority that bounds in-flight activations.  Construction
    guarantees precedence and one-chunk-per-device-per-tick.  The measured
    span T beats the GPipe-equivalent cost V·(M + P − 1) when ``M`` is a
    multiple of ``P`` (the Megatron-LM interleaving condition) and never
    exceeds it; for M % P != 0 the greedy schedule can only tie GPipe
    (e.g. every M ≡ 1 (mod P) config does).  Both bounds are asserted in
    tests, not assumed.
    """
    L = P * V
    avail: list[dict] = [dict() for _ in range(P)]
    for m in range(M):
        avail[0][(m, 0)] = 0  # stage-0 inputs come straight from xs
    slot_of: list[dict] = [dict() for _ in range(P)]
    free: list[list[int]] = [list(range(L * M)) for _ in range(P)]
    arriving: list[tuple | None] = [None] * P  # (m, v) landing at tick start
    cols: dict[str, list] = {k: [] for k in
                             ("exec_v", "exec_m", "recv_slot", "read_slot", "out_m")}
    remaining = L * M
    max_slot = -1
    t = 0
    while remaining or any(a is not None for a in arriving):
        row = {k: [-1] * P for k in cols}
        # 1. arrivals (sender executed last tick)
        for p in range(P):
            if arriving[p] is not None:
                m, v = arriving[p]
                s = free[p].pop(0)
                max_slot = max(max_slot, s)
                slot_of[p][(m, v)] = s
                avail[p][(m, v)] = t
                row["recv_slot"][p] = s
        arriving = [None] * P
        # 2. execution (drain-first: highest v, then lowest m)
        for p in range(P):
            ready = [(m, v) for (m, v), tk in avail[p].items() if tk <= t]
            if not ready:
                continue
            m, v = min(ready, key=lambda mv: (-mv[1], mv[0]))
            del avail[p][(m, v)]
            row["exec_v"][p], row["exec_m"][p] = v, m
            if (m, v) in slot_of[p]:
                s = slot_of[p].pop((m, v))
                row["read_slot"][p] = s
                free[p].insert(0, s)
            c = v * P + p
            if c == L - 1:
                row["out_m"][p] = m
            else:
                arriving[(p + 1) % P] = (m, v + (1 if p == P - 1 else 0))
            remaining -= 1
        for k in cols:
            cols[k].append(row[k])
        t += 1
        if t > 4 * L * M + L:  # pragma: no cover - schedule bug guard
            raise RuntimeError("interleave scheduler did not converge")
    return _InterleaveSchedule(
        T=t, Q=max(max_slot + 1, 1),
        **{k: np.asarray(v, np.int32) for k, v in cols.items()},
    )


def interleave_params(stacked, n_stages: int, virtual_stages: int):
    """Permute a ``[P·V, ...]``-stacked param pytree from chunk order into
    device-contiguous order for the ``P(stage)`` sharding.

    Chunk ``c`` of the logical layer stack runs on device ``c mod P`` as its
    local slice ``c // P`` (round-robin — the interleaving).  The mesh
    shards the leading axis contiguously, so position ``p·V + v`` of the
    sharded array must hold chunk ``v·P + p``.
    """
    P, V = n_stages, virtual_stages
    idx = np.array([v * P + p for p in range(P) for v in range(V)])

    def perm(leaf):
        return leaf[idx] if (
            hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == P * V
        ) else leaf

    return jax.tree.map(perm, stacked)


def make_interleaved_pipeline_train_step(
    block_fn: StageFn,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    num_microbatches: int,
    virtual_stages: int,
    state_example,
    data_axis: str = "data",
    stage_axis: str = "stage",
    remat: bool = False,
    donate: bool = True,
):
    """Interleaved (virtual-stage) pipeline of HOMOGENEOUS blocks.

    Like :func:`make_stacked_pipeline_train_step`, but each device holds
    ``virtual_stages`` chunk slices of the ``P·V``-deep stack assigned
    round-robin (chunk ``c`` → device ``c mod P``), so micro-batches loop
    around the device ring ``V`` times.  With ``num_microbatches`` a
    multiple of ``P`` (the Megatron-LM interleaving condition — use it;
    other values are correct but can degenerate to GPipe's span), the
    fill/drain bubble shrinks from GPipe's ``(P−1)/(M+P−1)`` of the span
    toward ``(P−1)/(V·M+P−1)`` — the Megatron-LM interleaved-schedule idea,
    here as ONE compiled SPMD scan driven by a static conflict-free
    schedule table (no host scheduler, no point-to-point runtime:
    activations ride one ``ppermute`` ring).

    ``state.params`` leaves must be stacked ``[P·V, ...]`` in DEVICE order —
    build chunk-ordered params, then apply :func:`interleave_params` before
    sharding.  The block must map activations to activations of the same
    shape.  ``jax.grad`` differentiates straight through the schedule; the
    reversed scan replays it backwards, which is exactly the interleaved
    backward schedule.
    """
    n_p = mesh.shape[stage_axis]
    V, M = virtual_stages, num_microbatches
    L = n_p * V
    sched = _interleave_schedule(n_p, V, M)
    tbl = {
        k: jnp.asarray(getattr(sched, k))
        for k in ("exec_v", "exec_m", "recv_slot", "read_slot", "out_m")
    }
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_example.params):
        if not (hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == L):
            raise ValueError(
                f"interleaved pipeline requires every param leaf stacked "
                f"[{L}, ...] (P·V); {jax.tree_util.keystr(path)} has shape "
                f"{getattr(leaf, 'shape', None)}"
            )
    state_specs = stacked_state_specs(state_example, L, stage_axis)

    def _step(state, batch):
        x, y = batch
        b = x.shape[0]
        _check_microbatchable(b, M)
        xs = x.reshape(M, b // M, *x.shape[1:])
        my_p = lax.axis_index(stage_axis)
        # this device's schedule columns, scanned as per-tick scalars
        cols = tuple(
            lax.dynamic_index_in_dim(tbl[k], my_p, axis=1, keepdims=False)
            for k in ("exec_v", "exec_m", "recv_slot", "read_slot", "out_m")
        )

        def local_loss(params):
            run = jax.checkpoint(block_fn) if remat else block_fn

            def tick(carry, col):
                buf, queue, outputs = carry
                ev, em, rs, ds, om = col
                # 1. bank the activation that arrived on the ring
                stored = lax.dynamic_update_index_in_dim(
                    queue, buf, jnp.clip(rs, 0), 0)
                queue = jnp.where(rs >= 0, stored, queue)
                # 2. fetch this tick's input (queue, or xs for chunk 0)
                from_q = lax.dynamic_index_in_dim(
                    queue, jnp.clip(ds, 0), 0, keepdims=False)
                x_in = lax.dynamic_index_in_dim(
                    xs, jnp.clip(em, 0), 0, keepdims=False)
                a_in = jnp.where((ds < 0) & (ev >= 0), x_in, from_q)
                # 3. run this device's scheduled chunk (idle ticks run on
                #    garbage — receivers discard via recv_slot=-1)
                p_v = jax.tree.map(
                    lambda pr: lax.dynamic_index_in_dim(
                        pr, jnp.clip(ev, 0), 0, keepdims=False),
                    params)
                y_out = run(p_v, a_in)
                # 4. bank final outputs (last chunk only)
                m_c = jnp.clip(om, 0)
                cur = lax.dynamic_index_in_dim(outputs, m_c, 0, keepdims=False)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(om >= 0, y_out, cur), m_c, 0)
                # 5. one hop down the ring
                buf = lax.ppermute(
                    y_out, stage_axis,
                    [(i, (i + 1) % n_p) for i in range(n_p)])
                return (buf, queue, outputs), None

            carry0 = (
                jnp.zeros_like(xs[0]),
                jnp.zeros((sched.Q, *xs.shape[1:]), xs.dtype),
                jnp.zeros_like(xs),
            )
            (_, _, outputs), _ = lax.scan(tick, carry0, cols)
            # masked-local loss on the device owning the last chunk
            # (chunk L-1 lives on device P-1); cotangents reach earlier
            # chunks through the transposed ppermute ring
            l = loss_fn(outputs.reshape(b, *outputs.shape[2:]), y)
            return jnp.where(my_p == n_p - 1, l, 0.0)

        loss, grads = jax.value_and_grad(local_loss)(state.params)
        grads = lax.pmean(grads, data_axis)
        metrics = {"loss": lax.pmean(lax.psum(loss, stage_axis), data_axis)}
        return state.apply_gradients(grads), metrics

    stepped = jit_sharded_step(
        _step, mesh, (state_specs, (P(data_axis), P(data_axis))),
        (state_specs, P()), donate,
    )

    def train_step(state, x, y):
        return stepped(state, (x, y))

    train_step.lower = lambda state, x, y: stepped.lower(state, (x, y))
    train_step.jitted = stepped
    train_step.schedule = sched
    # forward-only chunk ticks: V·M useful per device over the span T
    train_step.bubble_fraction = (sched.T - V * M) / sched.T
    return train_step
