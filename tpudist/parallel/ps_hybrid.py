"""Parameter-server-style hybrid parallelism: model-axis-sharded embedding
table + data-parallel dense layers on a 2-D mesh.

TPU-native replacement for the reference's 4-role RPC topology
(`server_model_data_parallel.py:114-185`): there, rank 3 ("ps") passively
hosts an ``EmbeddingBag(100, 16)`` reached via ``RemoteModule`` RPC lookups
(`:134-139`), two trainer ranks run DDP over a local ``Linear(16, 8)``
(`:34-46`), and ``dist_autograd`` routes embedding gradients trainer→ps
while gloo allreduces the dense grads (`:96-105`).  Here the same placement
is a sharding on a ``data × model`` mesh:

* the embedding table shards row-wise over ``model`` (each device column owns
  ``V / model`` rows — the "parameter server" dissolved into a sharding);
* the lookup is a local masked gather + one ``psum`` over ``model`` (the RPC
  round-trip become an ICI all-reduce *inside* the compiled step);
* dense layers replicate and sync via the data-axis ``pmean`` (the DDP
  equivalent);
* ``jax.grad`` routes embedding cotangents back through the psum transpose —
  the trainer→ps gradient RPC with no RPC.

Sharding-aware autodiff note (applies to every differentiated cross-shard
reduction in tpudist, see also :mod:`pipeline`): with ``check_vma=False``
the transpose of ``psum`` is ``psum``, so if the post-reduction computation
ran replicated on every shard the cotangents would be over-counted by the
shard count.  The pattern used here is to MASK the loss to one shard column
(``lax.axis_index(model) == 0``); the psum transpose then delivers exactly
one copy of the cotangent to every shard, and replicated values (dense
grads, metrics) are re-assembled with explicit psums *outside* the
differentiated path.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.common import jit_sharded_step

# dense_apply(fc_params, bag[b, D]) -> logits[b, C]
DenseApply = Callable[[Any, jnp.ndarray], jnp.ndarray]


def sharded_bag_lookup(
    local_table: jnp.ndarray,
    indices: jnp.ndarray,
    mask: jnp.ndarray,
    model_axis: str = "model",
) -> jnp.ndarray:
    """Masked bag-sum lookup over a row-sharded embedding table.

    ``local_table``: this shard's rows ``[V_local, D]``; global row ``r``
    lives on shard ``r // V_local``.  Out-of-shard indices contribute zero;
    one ``psum`` over ``model_axis`` assembles the full bag sums
    (mode="sum" semantics of the reference's EmbeddingBag,
    `server_model_data_parallel.py:136`).
    """
    v_local = local_table.shape[0]
    offset = lax.axis_index(model_axis) * v_local
    loc = indices - offset
    in_shard = (loc >= 0) & (loc < v_local)
    rows = jnp.take(local_table, jnp.clip(loc, 0, v_local - 1), axis=0)
    weight = jnp.where(in_shard, mask.astype(rows.dtype), 0.0)
    contrib = jnp.einsum("blh,bl->bh", rows, weight)
    return lax.psum(contrib, model_axis)


def ps_state_specs(state, table_key: str = "embedding", model_axis: str = "model"):
    """PartitionSpec pytree: leaves whose pytree path contains ``table_key``
    (the table itself and its mirrored optimizer moments) shard row-wise over
    ``model_axis``; everything else replicates.  Matching by path, not by
    shape, so a dense kernel that happens to share the table's dimensions is
    never mis-sharded."""

    def leaf_spec(path, leaf):
        in_table = any(
            isinstance(p, jax.tree_util.DictKey) and p.key == table_key
            for p in path
        )
        if in_table and hasattr(leaf, "ndim") and leaf.ndim >= 1:
            return P(model_axis)
        return P()

    return jax.tree_util.tree_map_with_path(leaf_spec, state)


def make_ps_hybrid_train_step(
    dense_apply: DenseApply,
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    state_example,
    num_embeddings: int,
    table_key: str = "embedding",
    data_axis: str = "data",
    model_axis: str = "model",
    donate: bool = True,
):
    """Build ``train_step(state, indices, mask, targets) -> (state, metrics)``.

    ``state.params`` must be ``{table_key: [V, D], ...dense params...}``
    (the :class:`tpudist.models.EmbeddingBagClassifier` layout); the table is
    placed row-sharded via :func:`ps_state_specs`, dense params replicated.
    Equivalent of ``_run_trainer``'s fwd/bwd/step
    (`server_model_data_parallel.py:93-105`), as one compiled program.
    """
    if num_embeddings % mesh.shape[model_axis]:
        raise ValueError(
            f"{num_embeddings} embedding rows not divisible by "
            f"{model_axis}={mesh.shape[model_axis]}"
        )
    state_specs = ps_state_specs(state_example, table_key, model_axis)

    def _step(state, batch):
        indices, mask, targets = batch

        def local_loss(params):
            bag = sharded_bag_lookup(
                params[table_key], indices, mask, model_axis
            )
            logits = dense_apply(
                {k: v for k, v in params.items() if k != table_key}, bag
            )
            l = loss_fn(logits, targets)
            # mask to model column 0 — see module docstring
            return jnp.where(lax.axis_index(model_axis) == 0, l, 0.0)

        loss, grads = jax.value_and_grad(local_loss)(state.params)
        synced = {}
        for k, g in grads.items():
            if k == table_key:
                # row grads live on the owning shard; only average data shards
                synced[k] = lax.pmean(g, data_axis)
            else:
                # dense grads were produced on model column 0 only — assemble
                # across model, average across data (the DDP allreduce,
                # `server_model_data_parallel.py:41`)
                synced[k] = lax.pmean(lax.psum(g, model_axis), data_axis)
        metrics = {"loss": lax.pmean(lax.psum(loss, model_axis), data_axis)}
        return state.apply_gradients(synced), metrics

    stepped = jit_sharded_step(
        _step, mesh, (state_specs, (P(data_axis), P(data_axis), P(data_axis))),
        (state_specs, P()), donate,
    )

    def train_step(state, indices, mask, targets):
        return stepped(state, (indices, mask, targets))

    return train_step


def make_ps_hybrid_forward(
    dense_apply: DenseApply,
    mesh: Mesh,
    state_example,
    num_embeddings: int,
    table_key: str = "embedding",
    data_axis: str = "data",
    model_axis: str = "model",
):
    """Inference: ``fn(params, indices, mask) -> logits`` (replicated)."""
    if num_embeddings % mesh.shape[model_axis]:
        raise ValueError(
            f"{num_embeddings} embedding rows not divisible by "
            f"{model_axis}={mesh.shape[model_axis]}"
        )
    param_specs = ps_state_specs(state_example, table_key, model_axis)

    def _fwd(params, indices, mask):
        bag = sharded_bag_lookup(params[table_key], indices, mask, model_axis)
        return dense_apply(
            {k: v for k, v in params.items() if k != table_key}, bag
        )

    return jit_sharded_step(
        _fwd, mesh, (param_specs, P(data_axis), P(data_axis)), P(data_axis),
        donate_first=False,
    )
