"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Absent from the reference (SURVEY.md §2.3: no attention model, no sequence
dimension anywhere), but first-class here: long-context training is the
workload whose communication pattern the reference's point-to-point RPC
transport (`model_parallel_ResNet50.py:173-174`) would have needed at scale,
re-expressed over ICI.

Two interchangeable strategies, both plugging into
:class:`tpudist.models.TransformerLM` via its ``attention_fn`` hook:

* :func:`ring_attention_fn` — blockwise attention with **online softmax**
  (flash-attention recurrence): each device keeps its Q block resident and
  the K/V blocks rotate around the mesh axis via ``lax.ppermute`` (one ICI
  hop per step, ``axis_size`` steps).  Memory per device is O(S/n · S/n) per
  block instead of O(S²); K/V transfer overlaps with the block matmuls in
  XLA's pipelined schedule.
* :func:`ulysses_attention_fn` — two ``lax.all_to_all``s swap the sharded
  axis: [B, S/n, H, D] → [B, S, H/n, D] (full sequence, head subset), plain
  attention, swap back.  Cheaper collectives on small meshes; requires
  ``num_heads % axis_size == 0``.

Both match :func:`tpudist.models.sdpa` bit-for-bit up to float tolerance —
tested against it in ``tests/test_ring_attention.py``.

Use inside any ``shard_map`` whose in_specs shard the sequence dimension;
:func:`make_sp_train_step` packages the full DP×SP transformer train step.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.parallel.common import jit_sharded_step

_NEG_BIG = -1e30  # finite stand-in for -inf: keeps the online-softmax
                  # recurrence NaN-free on fully-masked rows


def ring_attention_fn(axis_name: str = "seq") -> Callable:
    """Return an ``AttentionFn`` computing exact attention over a
    sequence-sharded axis by rotating K/V around the ring.

    Must be called inside a ``shard_map`` over ``axis_name``; q/k/v are the
    per-shard blocks [B, S/n, H, D] in global sequence order (shard i holds
    positions [i·S/n, (i+1)·S/n)).
    """

    def attend(q, k, v, *, causal: bool = True):
        n = lax.axis_size(axis_name)
        my = lax.axis_index(axis_name)
        b, s_loc, h, d = q.shape
        scale = d ** -0.5
        q_pos = my * s_loc + jnp.arange(s_loc)  # global positions of Q rows

        qf = q.astype(jnp.float32)
        m0 = jnp.full((b, h, s_loc), _NEG_BIG, jnp.float32)
        l0 = jnp.zeros((b, h, s_loc), jnp.float32)
        o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
        # K/V blocks travel the ring; `src` rides along so each device knows
        # which global block it currently holds (for the causal mask).
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(carry, _):
            kb, vb, src, m, l, o = carry
            k_pos = src * s_loc + jnp.arange(s_loc)
            logits = jnp.einsum(
                "bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)) * scale
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
            blk_max = jnp.max(logits, axis=-1)                 # [B,H,Sq]
            new_m = jnp.maximum(m, jnp.maximum(blk_max, _NEG_BIG))
            p = jnp.exp(logits - new_m[..., None])             # masked → 0
            corr = jnp.exp(m - new_m)
            l = l * corr + jnp.sum(p, axis=-1)
            o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
                "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
            kb, vb = lax.ppermute((kb, vb), axis_name, perm)
            src = lax.ppermute(src, axis_name, perm)
            return (kb, vb, src, new_m, l, o), None

        init = (k, v, my, m0, l0, o0)
        (_, _, _, _, l, o), _ = lax.scan(body, init, None, length=n)
        denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return (o / denom).astype(q.dtype)

    return attend


def ulysses_attention_fn(axis_name: str = "seq") -> Callable:
    """All-to-all sequence parallelism: trade the sharded sequence axis for
    a sharded head axis around an exact full-sequence attention."""

    def attend(q, k, v, *, causal: bool = True):
        from tpudist.models.transformer import sdpa

        def gather_heads(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
            return lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True)

        def scatter_heads(x):  # inverse
            return lax.all_to_all(
                x, axis_name, split_axis=1, concat_axis=2, tiled=True)

        out = sdpa(gather_heads(q), gather_heads(k), gather_heads(v),
                   causal=causal)
        return scatter_heads(out)

    return attend


def make_sp_train_step(
    model,
    loss_per_token: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    total_tokens: int,
    data_axis: str = "data",
    seq_axis: str = "seq",
    donate: bool = True,
):
    """DP×SP transformer train step: batch sharded over ``data``, sequence
    sharded over ``seq``, params replicated.

    ``model`` is a :class:`TransformerLM` **constructed with the matching
    attention_fn** (``ring_attention_fn(seq_axis)`` or
    ``ulysses_attention_fn(seq_axis)``); this helper wires positions, the
    global-mean loss, and the grad psum over both axes.

    ``loss_per_token(logits, targets) -> [tokens]`` returns UNREDUCED per-
    token losses; the step sums locally and normalises by ``total_tokens``
    so the cross-shard ``psum`` of gradients is exactly the global-batch
    gradient.
    """

    def _step(state, batch):
        tokens, targets = batch  # local views [B/nd, S/ns]
        s_loc = tokens.shape[1]
        seq_idx = lax.axis_index(seq_axis)
        positions = seq_idx * s_loc + jnp.arange(s_loc)[None, :]

        def local_loss(params):
            logits = model.apply({"params": params}, tokens,
                                 positions=positions)
            per_tok = loss_per_token(
                logits.reshape(-1, logits.shape[-1]), targets.reshape(-1))
            return jnp.sum(per_tok) / total_tokens

        loss, grads = jax.value_and_grad(local_loss)(state.params)
        grads = lax.psum(grads, (data_axis, seq_axis))
        loss = lax.psum(loss, (data_axis, seq_axis))
        return state.apply_gradients(grads), {"loss": loss}

    stepped = jit_sharded_step(
        _step, mesh,
        (P(), (P(data_axis, seq_axis), P(data_axis, seq_axis))),
        (P(), P()),
        donate,
    )

    def train_step(state, tokens, targets):
        return stepped(state, (tokens, targets))

    return train_step


def sp_forward(
    model,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "seq",
):
    """Sequence-parallel forward pass: ``fn(params, tokens) -> logits``
    with tokens/logits sharded [data, seq]."""

    def _fwd(params, tokens):
        s_loc = tokens.shape[1]
        positions = lax.axis_index(seq_axis) * s_loc + \
            jnp.arange(s_loc)[None, :]
        return model.apply({"params": params}, tokens, positions=positions)

    return jit_sharded_step(
        _fwd, mesh,
        (P(), P(data_axis, seq_axis)),
        P(data_axis, seq_axis),
        donate_first=False,
    )
