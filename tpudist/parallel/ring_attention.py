"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Absent from the reference (SURVEY.md §2.3: no attention model, no sequence
dimension anywhere), but first-class here: long-context training is the
workload whose communication pattern the reference's point-to-point RPC
transport (`model_parallel_ResNet50.py:173-174`) would have needed at scale,
re-expressed over ICI.

Three interchangeable strategies, all plugging into
:class:`tpudist.models.TransformerLM` via its ``attention_fn`` hook:

* :func:`ring_attention_fn` — blockwise attention with **online softmax**
  (flash-attention recurrence): each device keeps its Q block resident and
  the K/V blocks rotate around the mesh axis via ``lax.ppermute`` (one ICI
  hop per step, ``axis_size`` steps).  Memory per device is O(S/n · S/n) per
  block instead of O(S²); K/V transfer overlaps with the block matmuls in
  XLA's pipelined schedule.
* :func:`ulysses_attention_fn` — two ``lax.all_to_all``s swap the sharded
  axis: [B, S/n, H, D] → [B, S, H/n, D] (full sequence, head subset), plain
  attention, swap back.  Cheaper collectives on small meshes; requires
  ``num_heads % axis_size == 0``.
* :func:`ring_flash_attention_fn` — the ring recurrence with the per-block
  compute done by the Pallas flash kernels (`tpudist.ops.flash_attention`)
  instead of materialized [S/n, S/n] logits, plus a ring-level
  ``custom_vjp`` whose backward rotates dK/dV accumulators with their
  blocks.  The production long-context path: linear memory per device in
  both directions.

Both match :func:`tpudist.models.sdpa` bit-for-bit up to float tolerance —
tested against it in ``tests/test_ring_attention.py``.

Use inside any ``shard_map`` whose in_specs shard the sequence dimension;
:func:`make_sp_train_step` packages the full DP×SP transformer train step.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudist.ops.flash_attention import (
    _UNSET,
    _auto_block,
    _flash_forward,
    flash_block_grads,
    flash_delta,
)
from tpudist.parallel.common import jit_sharded_step

_NEG_BIG = -1e30  # finite stand-in for -inf: keeps the online-softmax
                  # recurrence NaN-free on fully-masked rows


def ring_attention_fn(axis_name: str = "seq") -> Callable:
    """Return an ``AttentionFn`` computing exact attention over a
    sequence-sharded axis by rotating K/V around the ring.

    Must be called inside a ``shard_map`` over ``axis_name``; q/k/v are the
    per-shard blocks [B, S/n, H, D] in global sequence order (shard i holds
    positions [i·S/n, (i+1)·S/n)).
    """

    def attend(q, k, v, *, causal: bool = True,
               window: int | None = None):
        from tpudist.models.transformer import repeat_kv

        if window is not None and not causal:
            raise ValueError("window requires causal=True")
        k, v = repeat_kv(q, k, v)  # GQA: naive path expands; ring flash
                                   # keeps K/V grouped (use it instead)
        n = lax.axis_size(axis_name)
        my = lax.axis_index(axis_name)
        b, s_loc, h, d = q.shape
        scale = d ** -0.5
        q_pos = my * s_loc + jnp.arange(s_loc)  # global positions of Q rows

        qf = q.astype(jnp.float32)
        m0 = jnp.full((b, h, s_loc), _NEG_BIG, jnp.float32)
        l0 = jnp.zeros((b, h, s_loc), jnp.float32)
        o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
        # K/V blocks travel the ring; `src` rides along so each device knows
        # which global block it currently holds (for the causal mask).
        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(carry, _):
            kb, vb, src, m, l, o = carry
            k_pos = src * s_loc + jnp.arange(s_loc)
            logits = jnp.einsum(
                "bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)) * scale
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
                if window is not None:
                    mask = mask & (
                        q_pos[:, None] - k_pos[None, :] < window)
                logits = jnp.where(mask[None, None], logits, -jnp.inf)
            blk_max = jnp.max(logits, axis=-1)                 # [B,H,Sq]
            new_m = jnp.maximum(m, jnp.maximum(blk_max, _NEG_BIG))
            p = jnp.exp(logits - new_m[..., None])             # masked → 0
            corr = jnp.exp(m - new_m)
            l = l * corr + jnp.sum(p, axis=-1)
            o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
                "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
            kb, vb = lax.ppermute((kb, vb), axis_name, perm)
            src = lax.ppermute(src, axis_name, perm)
            return (kb, vb, src, new_m, l, o), None

        init = (k, v, my, m0, l0, o0)
        (_, _, _, _, l, o), _ = lax.scan(body, init, None, length=n)
        denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
        return (o / denom).astype(q.dtype)

    return attend


def ulysses_attention_fn(axis_name: str = "seq") -> Callable:
    """All-to-all sequence parallelism: trade the sharded sequence axis for
    a sharded head axis around an exact full-sequence attention."""

    def attend(q, k, v, *, causal: bool = True,
               window: int | None = None):
        from tpudist.models.transformer import repeat_kv, sdpa

        n = lax.axis_size(axis_name)
        if k.shape[2] % n:
            # GQA with fewer KV heads than the axis: expand first (head
            # counts must divide the split)
            k, v = repeat_kv(q, k, v)
        # else: K/V stay GROUPED through the all-to-all — sdpa handles GQA
        # natively, so the K/V transport shrinks by the group factor

        def gather_heads(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
            return lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True)

        def scatter_heads(x):  # inverse
            return lax.all_to_all(
                x, axis_name, split_axis=1, concat_axis=2, tiled=True)

        out = sdpa(gather_heads(q), gather_heads(k), gather_heads(v),
                   causal=causal, window=window)
        return scatter_heads(out)

    return attend


def make_sp_train_step(
    model,
    loss_per_token: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    mesh: Mesh,
    total_tokens: int,
    data_axis: str = "data",
    seq_axis: str = "seq",
    donate: bool = True,
):
    """DP×SP transformer train step: batch sharded over ``data``, sequence
    sharded over ``seq``, params replicated.

    ``model`` is a :class:`TransformerLM` **constructed with the matching
    attention_fn** (``ring_attention_fn(seq_axis)`` or
    ``ulysses_attention_fn(seq_axis)``); this helper wires positions, the
    global-mean loss, and the grad psum over both axes.

    ``loss_per_token(logits, targets) -> [tokens]`` returns UNREDUCED per-
    token losses; the step sums locally and normalises by ``total_tokens``
    so the cross-shard ``psum`` of gradients is exactly the global-batch
    gradient.
    """

    def _step(state, batch):
        tokens, targets = batch  # local views [B/nd, S/ns]
        s_loc = tokens.shape[1]
        seq_idx = lax.axis_index(seq_axis)
        positions = seq_idx * s_loc + jnp.arange(s_loc)[None, :]

        def local_loss(params):
            logits = model.apply({"params": params}, tokens,
                                 positions=positions)
            per_tok = loss_per_token(
                logits.reshape(-1, logits.shape[-1]), targets.reshape(-1))
            return jnp.sum(per_tok) / total_tokens

        loss, grads = jax.value_and_grad(local_loss)(state.params)
        grads = lax.psum(grads, (data_axis, seq_axis))
        loss = lax.psum(loss, (data_axis, seq_axis))
        return state.apply_gradients(grads), {"loss": loss}

    stepped = jit_sharded_step(
        _step, mesh,
        (P(), (P(data_axis, seq_axis), P(data_axis, seq_axis))),
        (P(), P()),
        donate,
    )

    def train_step(state, tokens, targets):
        return stepped(state, (tokens, targets))

    return train_step


def sp_forward(
    model,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "seq",
):
    """Sequence-parallel forward pass: ``fn(params, tokens) -> logits``
    with tokens/logits sharded [data, seq]."""

    def _fwd(params, tokens):
        s_loc = tokens.shape[1]
        positions = lax.axis_index(seq_axis) * s_loc + \
            jnp.arange(s_loc)[None, :]
        return model.apply({"params": params}, tokens, positions=positions)

    return jit_sharded_step(
        _fwd, mesh,
        (P(), P(data_axis, seq_axis)),
        P(data_axis, seq_axis),
        donate_first=False,
    )


# --------------------------------------------------------------------------
# Ring flash attention: the ring recurrence with Pallas kernels per block
# --------------------------------------------------------------------------

def _rowstat_to_bshd(c: jnp.ndarray) -> jnp.ndarray:
    """[B, H, S] row statistic → [B, S, H, 1] for broadcasting over D."""
    return c.transpose(0, 2, 1)[..., None]


def _ring_perm(n: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % n) for i in range(n)]


def _ring_flash_fwd_impl(q, k, v, causal, axis_name, block_q, block_k,
                         interpret, window=None):
    """Rotate K/V blocks around the ring; each step runs the Pallas flash
    forward on the resident block with GLOBAL position offsets (the kernel
    masks and skips dead tiles itself), then merges (out, lse) pairs with
    the log-sum-exp identity.  Returns (out, final lse)."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), _NEG_BIG, jnp.float32)
    perm = _ring_perm(n)

    def body(carry, _):
        kb, vb, src, o, lse = carry
        ob, lse_b = _flash_forward(
            q, kb, vb, causal, block_q, block_k, interpret,
            q_offset=my * s_loc, k_offset=src * s_loc, window=window)
        new_lse = jnp.logaddexp(lse, lse_b)
        o = (o * _rowstat_to_bshd(jnp.exp(lse - new_lse))
             + ob.astype(jnp.float32)
             * _rowstat_to_bshd(jnp.exp(lse_b - new_lse)))
        kb, vb = lax.ppermute((kb, vb), axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (kb, vb, src, o, new_lse), None

    (_, _, _, o, lse), _ = lax.scan(body, (k, v, my, o0, lse0), None, length=n)
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, causal, axis_name, block_q, block_k, interpret,
                window):
    out, _ = _ring_flash_fwd_impl(
        q, k, v, causal, axis_name, block_q, block_k, interpret, window)
    return out


def _ring_flash_fwd(q, k, v, causal, axis_name, block_q, block_k, interpret,
                    window):
    out, lse = _ring_flash_fwd_impl(
        q, k, v, causal, axis_name, block_q, block_k, interpret, window)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(causal, axis_name, block_q, block_k, interpret, window,
                    res, dout):
    """Backward ring: dK/dV accumulators travel WITH their K/V blocks (one
    full loop lands them back on the owner), dQ accumulates locally.  Each
    step is the Pallas flash backward on the resident block, valid per
    block because P is recomputed against the FINAL lse."""
    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_loc = q.shape[1]
    delta = flash_delta(out, dout)
    perm = _ring_perm(n)

    def body(carry, _):
        kb, vb, dk, dv, src, dq = carry
        dq_b, dk_b, dv_b = flash_block_grads(
            q, kb, vb, dout, lse, delta,
            causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret, q_offset=my * s_loc, k_offset=src * s_loc,
            window=window)
        dq = dq + dq_b.astype(jnp.float32)
        dk = dk + dk_b.astype(jnp.float32)
        dv = dv + dv_b.astype(jnp.float32)
        kb, vb, dk, dv = lax.ppermute((kb, vb, dk, dv), axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (kb, vb, dk, dv, src, dq), None

    init = (k, v, jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32), my,
            jnp.zeros(q.shape, jnp.float32))
    (_, _, dk, dv, _, dq), _ = lax.scan(body, init, None, length=n)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention_fn(
    axis_name: str = "seq",
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    window: int | None = None,
) -> Callable:
    """:func:`ring_attention_fn` with the per-block compute done by the
    Pallas flash kernels instead of materialized [S/n, S/n] logits: VMEM
    blocking within a device, ``ppermute`` ring across devices — the same
    online-softmax recurrence at both levels.  Gradients run a second ring
    (dK/dV ride the rotating blocks home); memory stays linear in S on
    every device in both directions."""
    factory_window = window

    def attend(q, k, v, *, causal: bool = True, window=_UNSET):
        window = factory_window if window is _UNSET else window
        s_loc = q.shape[1]
        if q.shape[2] % k.shape[2]:
            raise ValueError(
                f"num_heads {q.shape[2]} must be a multiple of kv heads "
                f"{k.shape[2]} (GQA)")
        bq = _auto_block(s_loc) if block_q is None else min(block_q, s_loc)
        bk = _auto_block(s_loc) if block_k is None else min(block_k, s_loc)
        if s_loc % bq or s_loc % bk:
            raise ValueError(
                f"block sizes ({bq}, {bk}) must divide the local "
                f"sequence length {s_loc}")
        if window is not None and (not causal or window < 1):
            raise ValueError(
                f"window={window} requires causal=True and window >= 1")
        itp = (
            (jax.default_backend() == "cpu") if interpret is None
            else interpret
        )
        return _ring_flash(q, k, v, causal, axis_name, bq, bk, itp, window)

    # See flash_attention_fn: lets TransformerLM reject a factory window
    # that disagrees with cfg.attention_window instead of discarding it.
    attend.factory_window = factory_window
    return attend
