"""Tensor (intra-layer model) parallelism via GSPMD sharding rules.

The reference has no tensor parallelism (SURVEY.md §2.3: "falls out of GSPMD
if wanted") — its model parallelism is inter-layer RPC placement
(`model_parallel_ResNet50.py:152-165`).  On TPU, intra-layer sharding is the
idiomatic way to scale a single layer past one chip, and it requires no
runtime mechanism at all: annotate each parameter with a
:class:`~jax.sharding.NamedSharding` over the ``model`` mesh axis and jit —
XLA/GSPMD partitions every matmul and inserts the all-reduces
(Megatron-style column→row pairing becomes a *layout choice*, not code).

The rule language here is path-pattern → :class:`PartitionSpec`.  For the
transformer zoo model the canonical Megatron layout ships as
:func:`transformer_tp_rules`:

* ``qkv``/``up`` kernels: column-sharded ``P(None, "model")`` (heads / mlp
  width split across chips; no communication in forward);
* ``proj``/``down`` kernels: row-sharded ``P("model", None)`` (the matching
  all-reduce after the second matmul of each pair);
* embeddings / lm_head: vocab-sharded;
* norms and everything unmatched: replicated.

Combined with a ``data``-sharded batch this gives hybrid DP×TP from a single
jit — the 2-D-mesh generalisation of the reference's hybrid example
(`server_model_data_parallel.py:34-46`).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudist.train.state import TrainState

# loss_fn(params, batch, rng) -> (scalar_loss, aux_dict) over the GLOBAL batch
LossFn = Callable[[Any, tuple, jax.Array], tuple[jnp.ndarray, dict]]

Rules = Sequence[tuple[str, P]]


def spec_tree_from_rules(params: Any, rules: Rules) -> Any:
    """Map a pytree of params to a pytree of PartitionSpecs.

    Each leaf's key-path is rendered as ``"a/b/c"`` and matched against the
    ``rules`` patterns (``re.search``, first match wins); unmatched leaves
    replicate (``P()``).
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(path, leaf) -> P:
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        for pat, spec in compiled:
            if pat.search(name):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def transformer_tp_rules(axis: str = "model") -> Rules:
    """Megatron-style layout for :class:`tpudist.models.TransformerLM`."""
    return [
        (r"attn/qkv/kernel", P(None, axis)),
        # GQA projections (column-parallel like qkv; requires
        # num_kv_heads % tp_size == 0 so each shard owns whole KV heads)
        (r"attn/q/kernel", P(None, axis)),
        (r"attn/kv/kernel", P(None, axis)),
        (r"attn/proj/kernel", P(axis, None)),
        (r"mlp/up/kernel", P(None, axis)),
        (r"mlp/down/kernel", P(axis, None)),
        (r"tok_embed/embedding", P(axis, None)),
        (r"pos_embed/embedding", P()),
        (r"lm_head/kernel", P(None, axis)),
    ]


def shard_tree(tree: Any, mesh: Mesh, specs: Any) -> Any:
    """``device_put`` each leaf with its NamedSharding (copying device-array
    leaves first so later buffer donation cannot free a caller's array)."""

    def put(x, spec):
        if isinstance(x, jax.Array):
            x = jnp.array(x, copy=True)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, tree, specs)


def shard_batch(batch: Any, mesh: Mesh, axis: str = "data") -> Any:
    """Shard every batch array along its leading (batch) dimension."""
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(axis))), batch
    )


def make_spmd_train_step(
    loss_fn: LossFn,
    mesh: Mesh,
    param_specs: Any,
    donate: bool = True,
):
    """Build ``train_step(state, *batch) -> (state, metrics)`` in GSPMD mode.

    Unlike the shard_map strategies (explicit per-shard code + collectives),
    this step is written as a GLOBAL program: ``loss_fn`` sees the full
    logical batch and full logical params; the compiler partitions it over
    the mesh from the shardings attached to the inputs.  Data-parallel
    gradient sync is the batch-mean's cross-shard reduce; tensor-parallel
    activation all-reduces come from the column→row kernel layouts.  One jit
    covers DP, TP, and DP×TP — the sharding rules are the strategy.

    ``param_specs`` is re-asserted inside the step (``with_sharding_constraint``)
    so the layout survives any optimizer re-sharding temptation XLA has.
    """

    def _step(state: TrainState, batch: tuple):
        params = jax.lax.with_sharding_constraint(state.params, param_specs)
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, state.rng
        )
        grads = jax.lax.with_sharding_constraint(grads, param_specs)
        new_state = state.apply_gradients(grads)
        return new_state, {"loss": loss, **aux}

    with mesh:
        stepped = jax.jit(_step, donate_argnums=(0,) if donate else ())

    def train_step(state, *batch):
        with mesh:
            return stepped(state, batch)

    # The underlying jit, exposed so callers/tests can inspect the compiled
    # schedule (.lower(...).compile().as_text()) — the FSDP/EP contracts
    # (reduce-scatter / all-gather / all-to-all) are ASSERTED against this
    # HLO rather than trusted to GSPMD (tests/test_fsdp.py, test_moe.py).
    train_step.jitted = stepped

    def _lower(state, *batch):
        with mesh:
            return stepped.lower(state, batch)

    train_step.lower = _lower  # cost-probe / MFU hook (obs.xla)
    return train_step


def make_tp_state(
    model_apply: Callable,
    params: Any,
    tx,
    mesh: Mesh,
    rules: Rules | None = None,
    rng: jax.Array | int = 0,
) -> tuple[TrainState, Any]:
    """Shard ``params`` by ``rules`` and build a TrainState whose optimizer
    state inherits the same shardings (``zeros_like`` of a committed sharded
    array keeps its sharding).  Returns ``(state, param_specs)``."""
    specs = spec_tree_from_rules(params, rules or transformer_tp_rules())
    sharded = shard_tree(params, mesh, specs)
    state = TrainState.create(model_apply, sharded, tx, rng=rng)
    return state, specs
