"""Runtime layer: device meshes, multi-host bootstrap, coordination.

TPU-native replacement for the reference's L1 communication/runtime layer
(gloo process groups, Horovod/MPI, TensorPipe RPC — SURVEY.md §1 L1): on TPU
the collective substrate is XLA over ICI/DCN, so "init_process_group" becomes
mesh construction + (multi-host) ``jax.distributed.initialize``.
"""

from tpudist.runtime.distributed import (
    DistributedContext,
    initialize,
    local_rank,
    process_count,
    process_index,
    world_info,
)
from tpudist.runtime.ici import (
    IciCollectives,
    IciDataPlane,
    host_snapshot,
    is_collective_failure,
)
from tpudist.runtime.mesh import (
    MeshSpec,
    data_mesh,
    data_model_mesh,
    get_devices,
    make_mesh,
    pipeline_mesh,
)

__all__ = [
    "DistributedContext",
    "IciCollectives",
    "IciDataPlane",
    "MeshSpec",
    "host_snapshot",
    "is_collective_failure",
    "data_mesh",
    "data_model_mesh",
    "get_devices",
    "initialize",
    "local_rank",
    "make_mesh",
    "pipeline_mesh",
    "process_count",
    "process_index",
    "world_info",
]
