"""Autoscaling control plane for the serve fleet.

ROADMAP item 4's policy layer: PR 6-8 built the *mechanisms* — live
join (:func:`~tpudist.runtime.router.scale_fleet`), graceful drain
(``{ns}/draining/{rid}`` + the replica's zero-loss close path), health
-aware routing — but a human still had to watch queue-wait percentiles
and call ``scale_fleet`` by hand.  This module closes the loop: a
rank-0 control process watches the SAME merged ``serve/queue_wait_s``
percentiles the router's SLO gate reads (sliding-window, so an
hours-old spike can neither mask fresh load nor pin the fleet up) plus
``serve/queue_depth`` / ``serve/kv_blocks_free``, and drives the fleet
itself.

Policy — target-tracking with ASYMMETRIC hysteresis:

* **Scale up** after ``breach_polls`` CONSECUTIVE polls with the
  watched percentile above ``target_wait_s`` (a single slow poll is
  noise; a sustained breach is load), bounded by ``max_replicas`` and
  an ``up_cooldown_s`` per-direction cooldown so one breach episode
  produces one scale-up, not one per poll while the joiner compiles.
  Joiners mid-warmup (spawned, not yet heartbeating) count toward the
  bound for the same reason.
* **Scale down** only after a MUCH longer sustained-idle window
  (``idle_polls`` consecutive polls below ``low_wait_s`` with an empty
  queue) plus ``down_cooldown_s``: adding capacity late costs SLO,
  removing it early costs a re-scale-up — so up is eager, down is
  reluctant.
* **Scale-down is a graceful drain, never a kill**: the victim gets a
  ``draining`` mark (the router steers admissions away immediately),
  its inbox empties, THEN the targeted stop key lands — the worker's
  close path finishes queued and in-flight work, commits every
  completion, and exits cleanly.  The autoscaler never loses a
  request; ``autoscale/drain_completed`` ticks only after the lease is
  gone and the coordination residue is swept.

Every knob is env-tunable (``TPUDIST_AUTOSCALE_*`` — see
:meth:`AutoscaleConfig.from_env`), and the control loop is a plain
:meth:`Autoscaler.poll` method so tests drive it deterministically
against a fake coordination client; :meth:`Autoscaler.start` wraps it
in the background thread a deployment runs.

The ``TPUDIST_FAULT_AUTOSCALE_POLL_DELAY_S`` injection stalls each
poll — a wedged control plane.  The data plane must keep serving
through it (the autoscaler holds no locks and sits on no request
path); scaling is merely late.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Callable, Sequence

from tpudist import obs
from tpudist.obs.aggregate import collect, merge_snapshots
from tpudist.obs.alerts import AlertManager, autoscale_rules
from tpudist.obs.registry import hist_quantile
from tpudist.obs.tsdb import TSDB
from tpudist.runtime import faults
from tpudist.runtime.coord import CoordClient
from tpudist.runtime.router import DEFAULT_NAMESPACE, scale_fleet
from tpudist.utils.logging import get_logger

log = get_logger(__name__)

__all__ = ["AutoscaleConfig", "Autoscaler"]

ENV_PREFIX = "TPUDIST_AUTOSCALE_"


def _env(environ, name: str) -> float | None:
    raw = environ.get(ENV_PREFIX + name)
    if raw is None or raw.strip() == "":
        return None
    return float(raw)


class AutoscaleConfig:
    """The target-tracking policy's knobs (all env-overridable).

    ``low_wait_s`` defaults to ``target_wait_s / 4``: the idle band and
    the breach band must not touch, or the policy oscillates at the
    boundary."""

    def __init__(self, *,
                 min_replicas: int = 1,
                 max_replicas: int = 4,
                 target_wait_s: float = 0.5,
                 low_wait_s: float | None = None,
                 quantile: float = 0.9,
                 breach_polls: int = 3,
                 idle_polls: int = 10,
                 up_cooldown_s: float = 5.0,
                 down_cooldown_s: float = 20.0,
                 poll_s: float = 0.5,
                 step: int = 1,
                 max_metric_age_s: float = 5.0,
                 max_burn_rate: float | None = None,
                 min_kv_free_frac: float | None = None,
                 min_tier_headroom_frac: float | None = None) -> None:
        if min_replicas < 0 or max_replicas < max(min_replicas, 1):
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas (>=1), got "
                f"{min_replicas}/{max_replicas}")
        if target_wait_s <= 0:
            raise ValueError(
                f"target_wait_s must be > 0, got {target_wait_s}")
        if low_wait_s is None:
            low_wait_s = target_wait_s / 4.0
        if not 0.0 <= low_wait_s < target_wait_s:
            raise ValueError(
                f"need 0 <= low_wait_s < target_wait_s, got "
                f"{low_wait_s} vs {target_wait_s}")
        if not 0.0 < quantile <= 1.0:
            raise ValueError(f"quantile must be in (0, 1], got {quantile}")
        if breach_polls < 1 or idle_polls < 1 or step < 1:
            raise ValueError("breach_polls, idle_polls and step must "
                             "all be >= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.target_wait_s = float(target_wait_s)
        self.low_wait_s = float(low_wait_s)
        self.quantile = float(quantile)
        self.breach_polls = int(breach_polls)
        self.idle_polls = int(idle_polls)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.poll_s = float(poll_s)
        self.step = int(step)
        self.max_metric_age_s = float(max_metric_age_s)
        if max_burn_rate is not None and max_burn_rate <= 0:
            raise ValueError(
                f"max_burn_rate must be > 0, got {max_burn_rate}")
        # SLO burn-rate pressure: a sustained burn above this (fleet
        # merged slo/burn_rate_* gauges, OR the local tracker) counts as
        # a breach poll even while queue-wait looks fine — bad outcomes
        # (sheds, timeouts, failures) scale the fleet up too, not just
        # slow queues.  None disables the signal.
        self.max_burn_rate = (None if max_burn_rate is None
                              else float(max_burn_rate))
        # KV-pressure up-signal: a poll where the pool's merged free
        # block fraction (free / (free + used)) sits below this counts
        # as a breach even with an empty queue — the DECODE pool's load
        # is resident cache, not queue wait, so waiting for queue-wait
        # breach means admissions are already stalling on pages.  None
        # disables the signal (the prefill pool's load IS queue wait).
        if min_kv_free_frac is not None and not 0.0 < min_kv_free_frac < 1.0:
            raise ValueError(f"min_kv_free_frac must be in (0, 1), got "
                             f"{min_kv_free_frac}")
        self.min_kv_free_frac = (None if min_kv_free_frac is None
                                 else float(min_kv_free_frac))
        # tiered-KV pressure up-signal: when the fleet's host spill
        # tiers run out of headroom (merged serve/tier_bytes vs
        # serve/tier_budget_bytes), the next evictions DISCARD warm
        # prefixes instead of spilling them — re-prefill load is about
        # to arrive even though queues still look fine.  A poll with
        # fleet tier headroom (1 - bytes/budget) below this counts as a
        # breach.  None disables the signal (and fleets with the tier
        # disabled publish no budget, which also disables it).
        if (min_tier_headroom_frac is not None
                and not 0.0 < min_tier_headroom_frac < 1.0):
            raise ValueError(
                f"min_tier_headroom_frac must be in (0, 1), got "
                f"{min_tier_headroom_frac}")
        self.min_tier_headroom_frac = (
            None if min_tier_headroom_frac is None
            else float(min_tier_headroom_frac))

    @classmethod
    def from_env(cls, environ=None, **overrides) -> "AutoscaleConfig":
        import os

        env = os.environ if environ is None else environ
        kw: dict = {}
        for name, key, cast in (
                ("MIN_REPLICAS", "min_replicas", int),
                ("MAX_REPLICAS", "max_replicas", int),
                ("TARGET_WAIT_S", "target_wait_s", float),
                ("LOW_WAIT_S", "low_wait_s", float),
                ("QUANTILE", "quantile", float),
                ("BREACH_POLLS", "breach_polls", int),
                ("IDLE_POLLS", "idle_polls", int),
                ("UP_COOLDOWN_S", "up_cooldown_s", float),
                ("DOWN_COOLDOWN_S", "down_cooldown_s", float),
                ("POLL_S", "poll_s", float),
                ("STEP", "step", int),
                ("MAX_METRIC_AGE_S", "max_metric_age_s", float),
                ("MAX_BURN_RATE", "max_burn_rate", float),
                ("MIN_KV_FREE_FRAC", "min_kv_free_frac", float),
                ("MIN_TIER_HEADROOM_FRAC", "min_tier_headroom_frac",
                 float)):
            v = _env(env, name)
            if v is not None:
                kw[key] = cast(v)
        kw.update(overrides)
        return cls(**kw)


class Autoscaler:
    """The rank-0 control loop.

    Args:
      client: coord client (the autoscaler's own).
      coord_addr: ``host:port`` handed to the default spawner
        (:func:`~tpudist.runtime.router.scale_fleet` with chain-
        allocated indices).  Optional when ``spawner`` is injected.
      config: the policy; defaults to :meth:`AutoscaleConfig.from_env`.
      spawner: ``spawner(n) -> list[Popen]`` override (tests inject a
        fake; multi-host deployments inject their pod launcher).
      replica_args / platform: forwarded to the default spawner so
        joiners run the same serve configuration as the fleet.
      pool: ``None`` (unified fleet — every replica) or a disaggregated
        stage, ``"prefill"`` / ``"decode"``.  A pool-scoped instance
        observes and acts ONLY on replicas registered with that role:
        its live/draining/quarantined views, metric merge, victim pick
        and spawner (joiners get ``--role {pool}``) all filter by the
        registration's role, and every ``autoscale/*`` metric it owns
        is suffixed ``~pool={pool}`` so the two control loops of a
        disaggregated fleet never collide.  The pools' load signals
        differ by design: prefill load is QUEUE WAIT (bursty compute),
        decode load is RESIDENT KV (steady memory) — size the decode
        pool with ``min_kv_free_frac``.
      clock: injectable monotonic clock (deterministic cooldown tests).

    :meth:`poll` is ONE control decision — observe, decide, act — and
    returns a record of what it saw and did.  :meth:`start` runs it on
    ``config.poll_s`` cadence in a daemon thread.
    """

    def __init__(self, client: CoordClient, *,
                 coord_addr: str | None = None,
                 namespace: str = DEFAULT_NAMESPACE,
                 config: AutoscaleConfig | None = None,
                 spawner: Callable[[int], list] | None = None,
                 replica_args: Sequence[str] = (),
                 env_extra: dict | None = None,
                 platform: str = "cpu",
                 pool: str | None = None,
                 clock=time.monotonic) -> None:
        if pool not in (None, "prefill", "decode"):
            raise ValueError(f"pool must be None, 'prefill' or 'decode', "
                             f"got {pool!r}")
        self.client = client
        self.ns = namespace
        self.pool = pool
        self.cfg = config or AutoscaleConfig.from_env()
        self.replica_args = list(replica_args)
        self.env_extra = dict(env_extra or {})
        self.platform = platform
        self._clock = clock
        if spawner is None:
            if coord_addr is None:
                raise ValueError(
                    "need coord_addr for the default scale_fleet "
                    "spawner (or inject spawner=)")
            spawner = self._default_spawner
        self.coord_addr = coord_addr
        self.spawner = spawner
        self.procs: list = []          # joiners spawned by this loop
        self._drains: set[str] = set()  # rids THIS loop marked draining
        self._breach = 0
        self._idle = 0
        self._poll_n = 0
        # bounded history of every poll's decision record (stamped with
        # the poll index and clock): the replayable control-plane
        # artifact the offline simulator (tpudist.sim) reproduces and
        # the sim-vs-live agreement check diffs against
        self.decision_log: list[dict] = []
        self.decision_log_max = 4096
        self._last_up: float | None = None
        self._last_down: float | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        tag = "" if pool is None else f"~pool={pool}"
        self._obs_ups = obs.counter(f"autoscale/scale_ups{tag}",
                                    unit="replicas")
        self._obs_downs = obs.counter(f"autoscale/scale_downs{tag}",
                                      unit="replicas")
        self._obs_drained = obs.counter(f"autoscale/drain_completed{tag}",
                                        unit="replicas")
        self._obs_drain_migrations = obs.counter(
            f"autoscale/drain_migrations{tag}", unit="reqs",
            help="requests a draining replica migrated out instead of "
                 "finishing in place (fast drain)")
        # fast-drain bookkeeping: a draining victim's serve/migrated_out
        # counter at drain start (base) and at the last tick (last) —
        # growth past base plus an empty queue means the work LEFT, so
        # the stop key must not keep waiting on completions
        self._drain_mig_base: dict[str, float] = {}
        self._drain_mig_last: dict[str, float] = {}
        self._obs_polls = obs.counter(f"autoscale/polls{tag}",
                                      unit="polls")
        self._obs_suppressed = obs.counter(
            f"autoscale/suppressed_polls{tag}", unit="polls",
            help="polls skipped because the coord store was unreachable "
                 "(no scaling verdicts on blind data)")
        self._obs_replicas = obs.gauge(f"autoscale/replicas{tag}",
                                       unit="replicas")
        self._obs_wait = obs.gauge(f"autoscale/wait_q{tag}", unit="s")
        self._obs_breach = obs.gauge(f"autoscale/breach_polls{tag}",
                                     unit="polls")
        self._obs_idle = obs.gauge(f"autoscale/idle_polls{tag}",
                                   unit="polls")
        self._obs_burn = obs.gauge(
            f"autoscale/burn_rate{tag}", unit="x",
            help="SLO burn rate the scaling decision saw (max of fleet "
                 "gauges and the local tracker's shortest window)")
        # breach predicates live in a declarative rule set evaluated
        # over a private per-poll TSDB (tpudist.obs.alerts): each poll
        # records what it observed as autoscale/* series and reads
        # which rules fire instead of re-implementing thresholds
        # inline.  Absent signals (no KV/tier gauges published) are
        # recorded as NaN — present but matching no predicate — so
        # "signal missing" can never read as a stale previous value.
        self._tsdb = TSDB(retention_s=600.0, resolution_s=0.001,
                          downsample_after_s=60.0, clock=self._clock)
        self.alerts = AlertManager(self._tsdb, autoscale_rules(self.cfg),
                                   clock=self._clock)

    def _default_spawner(self, n: int) -> list:
        args = list(self.replica_args)
        if self.pool is not None and "--role" not in args:
            args += ["--role", self.pool]
        return scale_fleet(self.coord_addr, n, namespace=self.ns,
                           replica_args=args,
                           env_extra=self.env_extra,
                           platform=self.platform)

    # -- fleet observation -------------------------------------------------

    def live(self) -> set[str]:
        mark = f"{self.ns}:"
        return {name[len(mark):] for name in self.client.live()
                if name.startswith(mark)}

    def draining(self) -> set[str]:
        prefix = f"{self.ns}/draining/"
        return {k[len(prefix):] for k in self.client.keys(prefix)}

    def quarantined(self) -> set[str]:
        """Replicas the router's quarantine manager has marked
        (``{ns}/quarantined/{rid}``): alive and heartbeating, but
        excluded from dispatch while golden probes decide their fate —
        so their capacity is MISSING and the fleet must backfill."""
        prefix = f"{self.ns}/quarantined/"
        return {k[len(prefix):] for k in self.client.keys(prefix)}

    def _registrations(self) -> dict[str, dict]:
        out = {}
        prefix = f"{self.ns}/replica/"
        for key in self.client.keys(prefix):
            raw = self.client.get(key)
            if raw is not None:
                out[key[len(prefix):]] = json.loads(raw.decode())
        return out

    def _pool_rids(self, regs: dict[str, dict]) -> set[str] | None:
        """Replicas this instance manages: ``None`` means ALL (the
        unified loop); a pool-scoped loop keeps only registrations
        carrying its role.  A live-but-unregistered joiner is invisible
        until it registers — its capacity-on-the-way is already counted
        through :meth:`_pending_joiners`."""
        if self.pool is None:
            return None
        return {rid for rid, info in regs.items()
                if info.get("role", "both") == self.pool}

    def _observe(self) -> dict:
        """The merged fleet view one decision is made from (pool-scoped
        instances see only their own pool's replicas and metrics)."""
        live = self.live()
        draining = self.draining()
        quarantined = self.quarantined()
        regs = self._registrations()
        # membership cutoff: only ranks still registered in
        # {ns}/replica/* contribute snapshots.  A departed publisher's
        # final sliding-window histogram otherwise stays pinned in the
        # merged quantiles until max_age_s — a dead replica's queue
        # waits steering live scaling decisions.  No registrations at
        # all means no membership information (a bare metrics-only
        # fleet): fall back to the age cutoff alone.
        members: set[int] | None = None
        if regs:
            members = set()
            for info in regs.values():
                try:
                    members.add(int(info.get("rank")))
                except (TypeError, ValueError):
                    continue
        snaps = collect(self.client, f"{self.ns}/metrics",
                        max_age_s=self.cfg.max_metric_age_s,
                        members=members)
        mine = self._pool_rids(regs)
        if mine is not None:
            live &= mine
            draining &= mine
            quarantined &= mine
            rank_to_rid = {int(info.get("rank", -1)): rid
                           for rid, info in regs.items()}
            snaps = {rank: s for rank, s in snaps.items()
                     if rank_to_rid.get(rank) in mine}
        merged = merge_snapshots(snaps)
        wait = merged["histograms"].get("serve/queue_wait_s")
        wait_q = (hist_quantile(wait, self.cfg.quantile)
                  if wait and wait["count"] else 0.0)
        if math.isnan(wait_q):
            wait_q = 0.0
        depth = (merged["gauges"].get("serve/queue_depth")
                 or {}).get("value") or 0.0
        free = (merged["gauges"].get("serve/kv_blocks_free")
                or {}).get("value")
        used = (merged["gauges"].get("serve/kv_blocks_used")
                or {}).get("value")
        kv_free_frac = (free / (free + used)
                        if free is not None and used is not None
                        and free + used > 0 else None)
        tier_bytes = (merged["gauges"].get("serve/tier_bytes")
                      or {}).get("value")
        tier_budget = (merged["gauges"].get("serve/tier_budget_bytes")
                       or {}).get("value")
        tier_headroom_frac = (1.0 - tier_bytes / tier_budget
                              if tier_bytes is not None
                              and tier_budget else None)
        # burn rate: worst across the fleet's published slo/burn_rate_*
        # gauges (per_worker max — summing rates across replicas would
        # overstate) and the local tracker's shortest window (a rank-0
        # router records its own terminal decisions into obs.slo)
        burn = 0.0
        for name, g in merged["gauges"].items():
            if name.startswith("slo/burn_rate_"):
                vals = [v for v in g.get("per_worker", {}).values()
                        if v is not None]
                if vals:
                    burn = max(burn, max(vals))
        local = obs.slo.burn_rates()
        if local:
            burn = max(burn, local[min(local)])
        return {"live": live, "draining": draining,
                "quarantined": quarantined, "wait_q": wait_q,
                "queue_depth": depth, "kv_blocks_free": free,
                "kv_free_frac": kv_free_frac,
                "tier_headroom_frac": tier_headroom_frac,
                "burn_rate": burn, "snaps": snaps}

    def _pending_joiners(self, live: set[str]) -> list:
        """Spawned-but-not-yet-heartbeating joiners: count them toward
        the max bound (and as capacity-on-the-way) so a breach episode
        during a joiner's compile doesn't stack a second scale-up."""
        return [p for p in self.procs
                if p.poll() is None
                and f"r{getattr(p, 'replica_index', -1)}" not in live]

    # -- the drain state machine (one tick per poll) -----------------------

    def _tick_drains(self, live: set[str], draining: set[str],
                     snaps: dict[int, dict] | None = None) -> None:
        """Advance in-progress graceful drains: a draining replica with
        an empty inbox gets its targeted stop key (its close path
        finishes all accepted work first — zero loss); one whose lease
        is gone gets its coordination residue swept.

        A drain finishes two ways: the work COMPLETES in place, or —
        fast drain, ``preempt="migrate"`` replicas — the work MIGRATES
        out as ``reason="migrate"`` commits the router redispatches.
        The drain decision therefore observes BOTH signals: inbox
        empty, or the victim advertising migrated-out-empty (its
        ``serve/migrated_out`` counter grew since the drain began and
        its queue-depth gauge is back to zero).  Without the second
        condition a fast drain deadlocks: the inbox can hold a key
        that raced the drain flag in while every completion the state
        machine is waiting for already left the replica.  Stopping on
        the migrated-out signal is still zero-loss — unconsumed inbox
        keys are swept and redispatched by the router's drain-departure
        path."""
        regs = self._registrations()
        rank_to_rid = {int(info.get("rank", -1)): rid
                       for rid, info in regs.items()}
        mig: dict[str, tuple[float, float]] = {}
        for rank, snap in (snaps or {}).items():
            rid = rank_to_rid.get(rank)
            if rid is None:
                continue
            mig[rid] = (
                (snap.get("counters", {}).get("serve/migrated_out")
                 or {}).get("value") or 0.0,
                (snap.get("gauges", {}).get("serve/queue_depth")
                 or {}).get("value") or 0.0)
        # union with the loop's own memory: the router's drain-
        # departure path may sweep the coord key first (it polls on the
        # request path and usually wins the race) — completion must be
        # counted either way
        for rid in sorted(draining | self._drains):
            if rid in live:
                migrated_clear = False
                if rid in mig:
                    out, depth = mig[rid]
                    base = self._drain_mig_base.setdefault(rid, out)
                    last = self._drain_mig_last.get(rid, base)
                    if out > last:
                        self._obs_drain_migrations.inc(out - last)
                        self._drain_mig_last[rid] = out
                    migrated_clear = out > base and depth <= 0.0
                if (self.client.get(f"{self.ns}/stop/{rid}") is None
                        and (migrated_clear or not self.client.keys(
                            f"{self.ns}/inbox/{rid}/"))):
                    self.client.set(f"{self.ns}/stop/{rid}", b"1")
                    log.info("autoscale: replica %s %s; stopping it",
                             rid, "migrated its work out"
                             if migrated_clear else "inbox empty")
                continue
            for key in (f"{self.ns}/draining/{rid}",
                        f"{self.ns}/stop/{rid}",
                        f"{self.ns}/replica/{rid}",
                        f"{self.ns}/metrics/"
                        f"{regs.get(rid, {}).get('rank')}"):
                try:
                    self.client.delete(key)
                except OSError:
                    pass
            self._drains.discard(rid)
            self._drain_mig_base.pop(rid, None)
            self._drain_mig_last.pop(rid, None)
            self._obs_drained.inc()
            log.info("autoscale: replica %s drain complete", rid)

    def _pick_victim(self, active: set[str],
                     snaps: dict[int, dict]) -> str | None:
        """Least-loaded active replica: fewest queued requests, then
        most free KV blocks — draining it strands the least work."""
        regs = self._registrations()
        rank_to_rid = {int(info.get("rank", -1)): rid
                       for rid, info in regs.items()}
        scores: dict[str, tuple] = {}
        for rank, snap in snaps.items():
            rid = rank_to_rid.get(rank)
            if rid not in active:
                continue
            gauges = snap.get("gauges", {})
            depth = (gauges.get("serve/queue_depth") or {}).get(
                "value") or 0.0
            free = (gauges.get("serve/kv_blocks_free") or {}).get("value")
            scores[rid] = (depth, -(free if free is not None
                                    else float("inf")))
        if not scores:
            return sorted(active)[0] if active else None
        return min(sorted(scores), key=lambda r: scores[r])

    # -- one control decision ----------------------------------------------

    def poll(self) -> dict:
        """Observe -> decide -> act, once.  Returns the decision record
        (tests assert on it; the bench logs it)."""
        faults.autoscale_poll()
        self._obs_polls.inc()
        try:
            view = self._observe()
        except ConnectionError as err:
            # coord brownout: the STORE is the unreachable thing, not
            # the fleet.  No scaling verdict is safe on blind data —
            # reset both hysteresis streaks (they must re-earn their
            # polls against fresh observations) and record a suppressed
            # poll instead of an action.
            self._breach = 0
            self._idle = 0
            self._obs_suppressed.inc()
            record = {"action": None, "pool": self.pool,
                      "suppressed": True,
                      "error": str(err), "poll": self._poll_n,
                      "t": self._clock()}
            self._poll_n += 1
            self.decision_log.append(record)
            if len(self.decision_log) > self.decision_log_max:
                del self.decision_log[:-self.decision_log_max]
            log.warning("autoscale: coord store unreachable (%s); "
                        "suppressing this poll", err)
            return record
        live, draining = view["live"], view["draining"]
        self._tick_drains(live, draining, view["snaps"])
        # quarantined capacity is MISSING capacity: the router will not
        # dispatch to it, so counting it would starve the backfill —
        # and it must never be picked as a scale-down victim (it is
        # already out of rotation; draining it would mask the probe
        # verdict that decides whether it comes back)
        active = live - draining - view["quarantined"]
        pending = self._pending_joiners(live)
        now = self._clock()
        action = None

        # breach detection reads FIRED ALERTS, not inline thresholds:
        # the poll records its observations into the private TSDB and
        # the rule set from autoscale_rules(cfg) — the same engine the
        # fleet operator rules run on — says which pressures hold.
        # Missing signals are NaN samples (match no predicate), so the
        # decision is identical to the historical inline comparisons.
        nan = float("nan")
        self._tsdb.record("autoscale/wait_q", view["wait_q"], t=now)
        self._tsdb.record("autoscale/burn_rate", view["burn_rate"], t=now)
        self._tsdb.record(
            "autoscale/kv_free_frac",
            view["kv_free_frac"] if view["kv_free_frac"] is not None
            else nan, t=now)
        self._tsdb.record(
            "autoscale/tier_headroom_frac",
            view["tier_headroom_frac"]
            if view["tier_headroom_frac"] is not None else nan, t=now)
        self.alerts.evaluate(now)
        fired = {a["rule"] for a in self.alerts.firing()}
        burning = "AutoscaleBurnRate" in fired
        # decode-pool pressure: resident KV, not queue wait — scale up
        # BEFORE admissions stall on pages
        starved = "AutoscaleKVStarved" in fired
        # tiered-KV pressure: spill tiers nearly full means warm
        # prefixes are about to be DISCARDED, not spilled — the
        # re-prefill load arrives before queue wait shows it
        tier_pressed = "AutoscaleTierPressure" in fired
        if "AutoscaleQueueWait" in fired or burning \
                or starved or tier_pressed:
            self._breach += 1
            self._idle = 0
        elif (view["wait_q"] < self.cfg.low_wait_s
              and view["queue_depth"] <= 0):
            self._idle += 1
            self._breach = 0
        else:
            # the hysteresis band: neither direction makes progress
            self._breach = 0
            self._idle = 0

        capacity = len(active) + len(pending)
        if (self._breach >= self.cfg.breach_polls
                and capacity < self.cfg.max_replicas
                and (self._last_up is None
                     or now - self._last_up >= self.cfg.up_cooldown_s)):
            n = min(self.cfg.step, self.cfg.max_replicas - capacity)
            why = ("kv_free_frac=%.2f < %.2f" % (
                       view["kv_free_frac"], self.cfg.min_kv_free_frac)
                   if starved else
                   "tier_headroom=%.2f < %.2f" % (
                       view["tier_headroom_frac"],
                       self.cfg.min_tier_headroom_frac)
                   if tier_pressed else
                   "wait p%d=%.3fs > target %.3fs" % (
                       int(self.cfg.quantile * 100), view["wait_q"],
                       self.cfg.target_wait_s))
            log.info("autoscale%s: %s for %d polls; scaling up by %d "
                     "(active=%d pending=%d)",
                     "" if self.pool is None else f"[{self.pool}]", why,
                     self._breach, n, len(active), len(pending))
            self.procs.extend(self.spawner(n))
            self._obs_ups.inc(n)
            self._last_up = now
            self._breach = 0
            action = ("up", n)
        elif (self._idle >= self.cfg.idle_polls
                and len(active) > self.cfg.min_replicas
                and not draining   # one graceful drain at a time
                and not pending
                and (self._last_down is None
                     or now - self._last_down
                     >= self.cfg.down_cooldown_s)):
            victim = self._pick_victim(active, view["snaps"])
            if victim is not None:
                log.info("autoscale: idle for %d polls (wait=%.3fs); "
                         "draining %s down", self._idle, view["wait_q"],
                         victim)
                self.client.set(f"{self.ns}/draining/{victim}", b"1")
                self._drains.add(victim)
                self._obs_downs.inc()
                self._last_down = now
                self._idle = 0
                action = ("down", victim)

        self._obs_replicas.set(len(active))
        self._obs_wait.set(view["wait_q"])
        self._obs_breach.set(self._breach)
        self._obs_idle.set(self._idle)
        self._obs_burn.set(view["burn_rate"])
        record = {"action": action, "pool": self.pool,
                  "wait_q": view["wait_q"],
                  "active": sorted(active), "draining": sorted(draining),
                  "quarantined": sorted(view["quarantined"]),
                  "pending": len(pending),
                  "queue_depth": view["queue_depth"],
                  "kv_free_frac": view["kv_free_frac"],
                  "burn_rate": view["burn_rate"],
                  "breach": self._breach, "idle": self._idle,
                  "poll": self._poll_n, "t": now}
        self._poll_n += 1
        self.decision_log.append(record)
        if len(self.decision_log) > self.decision_log_max:
            del self.decision_log[:-self.decision_log_max]
        return record

    def action_seq(self) -> list[dict]:
        """The non-None decisions, in order: ``[{"poll", "t", "kind",
        "arg"}, ...]`` — the compact sequence the simulator-vs-live
        agreement check compares (a drain victim's rid is live-run
        specific, so ``arg`` keeps only scale-up counts)."""
        out = []
        for r in self.decision_log:
            if r["action"] is None:
                continue
            kind, arg = r["action"]
            out.append({"poll": r["poll"], "t": r["t"], "kind": kind,
                        "arg": (arg if kind == "up" else None)})
        return out

    # -- background loop ---------------------------------------------------

    def start(self) -> None:
        """Run :meth:`poll` on ``config.poll_s`` cadence in a daemon
        thread until :meth:`stop`.  Poll errors are logged and retried
        next tick — a flaky coord RPC must not kill the control
        plane."""
        if self._thread is not None:
            return
        self._stop.clear()

        # treat loop start as the most recent scale-down: a freshly
        # started control plane sees an idle fleet for the first few
        # polls (no traffic has produced metrics yet) and must not
        # drain capacity before down_cooldown_s of real observation
        if self._last_down is None:
            self._last_down = self._clock()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.poll()
                except Exception as e:  # noqa: BLE001
                    # the control plane must outlive any single bad
                    # poll (flaky RPC, torn metrics JSON, ...)
                    log.warning("autoscale: poll failed (%r); retrying "
                                "next tick", e)
                self._stop.wait(self.cfg.poll_s)

        self._thread = threading.Thread(target=loop,
                                        name="tpudist-autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
