"""Persistent XLA compilation cache.

First-compile latency on a real TPU backend can reach minutes for scanned
train loops (conv nets under ``lax.scan``); a persistent on-disk cache makes
every subsequent process start warm.  The reference has no analog (eager
PyTorch compiles nothing); for tpudist the cache is what keeps the
compile-once-run-everywhere contract cheap across process restarts — which
elastic training does constantly (SURVEY.md §5 "failure detection":
recovery = process restart + re-jit).
"""

from __future__ import annotations

import os

import jax

DEFAULT_CACHE_DIR = os.path.expanduser("~/.cache/tpudist_xla")


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Turn on JAX's persistent compilation cache (idempotent).

    Honors ``TPUDIST_CACHE_DIR``; pass ``cache_dir`` to override.  Returns
    the directory in use.
    """
    cache_dir = (cache_dir or os.environ.get("TPUDIST_CACHE_DIR")
                 or DEFAULT_CACHE_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache everything that took meaningful compile time; the default
    # threshold (1s) skips tiny programs that are cheap to rebuild.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # same motivation, same call site: a process that cares about compile
    # cost wants the xla/compiles counter + duration histogram too (the
    # recompile-storm detector); idempotent, no-op if jax lacks the hooks
    from tpudist.obs.xla import install_compile_telemetry

    install_compile_telemetry()
    return cache_dir
