"""Host-side collectives over the native coordination store.

The reference's elastic paths lean on CPU collective backends — Gloo for DDP
(`mnist_ddp_elastic.py:26`) and Horovod's controller for the elastic driver
(`horovod_mnist_elastic.py:35,55`) — whose defining property is that the
*membership* of a collective is renegotiable at run time.  XLA's ICI
collectives (the tpudist data plane) are compiled for a fixed mesh; this
module provides the complementary control-plane collectives with DYNAMIC
membership, built on the C++ TCP store (``native/coord.cpp``): allreduce /
broadcast / barrier whose participant set is whatever the current rendezvous
round agreed on.

That property is what makes in-process elastic resize possible
(:mod:`tpudist.elastic.worker`): when a worker dies mid-allreduce, the
surviving participants' waits time out against the TTL-expired live set and
surface :class:`~tpudist.elastic.loop.WorldChanged` — they re-rendezvous
smaller and the next round's collectives simply have fewer participants.
No compiled program needs to change, because these collectives live outside
XLA.

Allreduce is BANDWIDTH-OPTIMAL, not naive (the Horovod-core trio the
reference gets for free from ``hvd.DistributedOptimizer``,
`mnist_horovod.py:53`):

* **bucket fusion** — the pytree is flattened into fused flat buffers of
  at most ``bucket_bytes`` (leaves grouped by dtype, concatenated in a
  deterministic order), so wire cost is per-bucket, not per-leaf;
* **ring reduce-scatter** — each fused bucket is split into ``world``
  chunks; chunk ``c`` travels the ring ``c → c+1 → … → c-1``, each hop
  adding its own contribution, so every rank uploads/downloads
  ``(world-1)/world`` of the payload instead of ``world×`` of it.  The
  all-gather half uses the store's star topology directly: the rank that
  finishes chunk ``c`` posts it ONCE and every peer fetches it — ring
  forwarding would double store traffic for nothing.  Net wire bytes per
  rank: ``2·(world−1)/world × size`` fetched (vs ``(world−1) × size``
  flat), ``~1 × size`` posted;
* **wire compression** — float32 payloads optionally travel as bf16
  (default) or fp16 with float32 accumulation at every hop
  (``coll/compress_ratio`` reports the saving); every other dtype rides
  raw;
* **async overlap** — :meth:`HostCollectives.allreduce_sum_async` returns
  a :class:`Handle` and runs post/fetch/reduce on a background worker, so
  the caller's next microbatch overlaps the previous one's wire time
  (``hvd.DistributedOptimizer`` semantics — see
  :class:`tpudist.elastic.worker.OverlappedGradSync`).

DETERMINISM CONTRACT: after any allreduce, every participant holds a
bitwise-identical result.  Ring: each chunk is reduced exactly once, by one
rank per hop in fixed ring order, and the finished chunk's *encoded bytes*
are what every rank decodes — no rank re-does a reduction another rank
already did.  Flat: every rank reduces in rank order over the *posted*
(wire-encoded) payloads, including its own, so compression rounding is
identical everywhere.  The two algorithms may differ from each other in
ULPs (different addition order); replicas never differ from each other.

Wire format: flat posts one fused blob per rank under
``{ns}/{round}/{op}/{rank}``; ring posts chunk partials under
``{ns}/{round}/{op}/rs/{bucket}/{step}/{rank}`` and finished chunks under
``{ns}/{round}/{op}/ag/{bucket}/{chunk}``.  Chunk payloads are raw bytes of
the wire dtype — both sides derive shapes/offsets from the (identical)
fusion plan, so no per-message header is needed.  Key GC: a participant
deletes every key it posted for ``op - 2`` when starting ``op`` — by then
every peer has consumed them (finishing ``op`` requires the whole ring to
have finished ``op - 1``), so the store stays O(ring keys × 2) per round.
"""

from __future__ import annotations

import dataclasses
import io
import os
import queue
import threading
import time
from typing import Any, Callable

import numpy as np

from tpudist import obs
from tpudist.runtime.coord import CoordClient


class PeerLost(RuntimeError):
    """A collective wait exceeded its deadline; membership likely changed."""


# ---------------------------------------------------------------------------
# configuration


@dataclasses.dataclass(frozen=True)
class CollectiveConfig:
    """Knobs for the host allreduce (Horovod's fusion-buffer/compression
    trio, `HOROVOD_FUSION_THRESHOLD` analog).

    * ``algorithm`` — ``auto`` (flat for tiny payloads or ``world <= 2``,
      ring otherwise), or force ``flat`` / ``ring``.
    * ``bucket_bytes`` — fused-buffer cap; one ring runs per bucket, so
      smaller buckets start their wire time earlier but cost more store
      round-trips.
    * ``compress`` — ``bf16`` (default) / ``fp16`` / ``none``; applies to
      float32 payloads only, accumulation stays float32.
    * ``flat_max_bytes`` — ``auto`` switches to ring above this payload
      size (the flat gather's one-post/one-fetch-per-peer latency beats
      the ring's ``2·world`` round-trips for small trees).

    Every field has a ``TPUDIST_COLL_*`` environment override (read by
    :meth:`from_env`, the default for :class:`HostCollectives`), so the
    elastic worker and the launcher-spawned gang pick the same plan
    without plumbing.
    """

    algorithm: str = "auto"
    bucket_bytes: int = 4 << 20
    compress: str = "bf16"
    flat_max_bytes: int = 64 << 10

    def __post_init__(self) -> None:
        if self.algorithm not in ("auto", "flat", "ring"):
            raise ValueError(f"unknown algorithm {self.algorithm!r}")
        if self.compress not in ("none", "bf16", "fp16"):
            raise ValueError(f"unknown compress {self.compress!r}")
        if self.bucket_bytes < 64:
            raise ValueError(f"bucket_bytes too small: {self.bucket_bytes}")

    @classmethod
    def from_env(cls) -> "CollectiveConfig":
        return cls(
            algorithm=os.environ.get("TPUDIST_COLL_ALGO", cls.algorithm),
            bucket_bytes=int(os.environ.get("TPUDIST_COLL_BUCKET_BYTES",
                                            cls.bucket_bytes)),
            compress=os.environ.get("TPUDIST_COLL_COMPRESS", cls.compress),
            flat_max_bytes=int(os.environ.get("TPUDIST_COLL_FLAT_MAX_BYTES",
                                              cls.flat_max_bytes)),
        )


# ---------------------------------------------------------------------------
# wire codecs + bucket fusion


def _bf16() -> np.dtype:
    import ml_dtypes  # jax dependency, always present with jax

    return np.dtype(ml_dtypes.bfloat16)


def _wire_dtype(native: np.dtype, compress: str) -> np.dtype:
    """The dtype a group's bytes travel as: float32 compresses to
    bf16/fp16 when asked; everything else (ints, bool, f64, and already-
    half floats) rides raw."""
    if native == np.float32 and compress != "none":
        return _bf16() if compress == "bf16" else np.dtype(np.float16)
    return native


def _accum_dtype(native: np.dtype) -> np.dtype:
    """float32 accumulation for <= 16-bit floats (the fp32-master-copy
    rule of mixed-precision allreduce); everything else accumulates in
    its own dtype (ints must stay exact, f64 must not narrow)."""
    if native == np.float16 or native == _bf16():
        return np.dtype(np.float32)
    return native


def _encode(arr: np.ndarray, wire: np.dtype) -> bytes:
    return np.ascontiguousarray(arr.astype(wire, copy=False)).tobytes()


def _decode(raw: bytes, wire: np.dtype, accum: np.dtype) -> np.ndarray:
    # frombuffer is zero-copy (read-only); the astype to the accumulation
    # dtype copies exactly when it has to
    return np.frombuffer(raw, dtype=wire).astype(accum, copy=False)


@dataclasses.dataclass
class _Bucket:
    group: str            # dtype token of the owning group
    data: np.ndarray      # 1-D accum-dtype slice of the group's fused vector
    wire: np.dtype
    accum: np.dtype

    @property
    def wire_nbytes(self) -> int:
        return len(self.data) * self.wire.itemsize


def _fuse(np_leaves: list[np.ndarray],
          cfg: CollectiveConfig) -> tuple[list[_Bucket], dict]:
    """Horovod-style tensor fusion: group leaves by dtype (sorted dtype
    token, leaf order within a group — deterministic on every rank),
    concatenate each group into one flat accumulation-dtype vector, and
    slice it into buckets of at most ``bucket_bytes`` wire bytes.

    Returns ``(buckets, plan)`` where ``plan`` maps group token ->
    ``(leaf_indices, native_dtype, group_vectors)`` for :func:`_defuse`.
    """
    groups: dict[str, list[int]] = {}
    for i, leaf in enumerate(np_leaves):
        groups.setdefault(leaf.dtype.str, []).append(i)
    buckets: list[_Bucket] = []
    plan: dict[str, tuple] = {}
    for token in sorted(groups):
        idxs = groups[token]
        native = np.dtype(token)
        accum = _accum_dtype(native)
        wire = _wire_dtype(native, cfg.compress)
        parts = [np_leaves[i].ravel() for i in idxs]
        fused = (np.concatenate(parts) if len(parts) > 1
                 else parts[0]).astype(accum, copy=False)
        per_bucket = max(1, cfg.bucket_bytes // wire.itemsize)
        group_buckets = [
            _Bucket(token, fused[lo:lo + per_bucket], wire, accum)
            for lo in range(0, len(fused), per_bucket)
        ] or ([_Bucket(token, fused, wire, accum)] if fused.size == 0 else [])
        buckets.extend(b for b in group_buckets if b.data.size)
        plan[token] = (idxs, native)
    return buckets, plan


def _defuse(reduced: dict[str, list[np.ndarray]], plan: dict,
            np_leaves: list[np.ndarray]) -> list[np.ndarray]:
    """Inverse of :func:`_fuse`: concatenate each group's reduced bucket
    vectors, cast back to the native dtype, and split into leaf shapes."""
    out: list[np.ndarray | None] = [None] * len(np_leaves)
    for token, (idxs, native) in plan.items():
        vecs = reduced.get(token, [])
        vec = (np.concatenate(vecs) if len(vecs) > 1
               else vecs[0] if vecs
               else np.empty(0, _accum_dtype(native)))
        vec = vec.astype(native, copy=False)
        off = 0
        for i in idxs:
            n = np_leaves[i].size
            out[i] = vec[off:off + n].reshape(np_leaves[i].shape)
            off += n
    return out  # type: ignore[return-value]


def _chunk_bounds(n: int, world: int) -> list[tuple[int, int]]:
    """Ring chunk boundaries: ``world`` near-equal slices of ``[0, n)``
    (first ``n % world`` chunks one element larger) — identical on every
    rank, tolerating ``n < world`` via empty chunks."""
    base, rem = divmod(n, world)
    bounds, lo = [], 0
    for c in range(world):
        hi = lo + base + (1 if c < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def _dumps(leaves: list[np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, *leaves)
    return buf.getvalue()


def _loads(raw: bytes) -> list[np.ndarray]:
    with np.load(io.BytesIO(raw)) as z:
        # index by position, not z.files order (lexicographic would put
        # arr_10 before arr_2)
        return [z[f"arr_{i}"] for i in range(len(z.files))]


# ---------------------------------------------------------------------------
# async plumbing


class Handle:
    """Result of an async collective: :meth:`wait` blocks until the
    background worker finishes and returns the reduced tree — or re-raises
    whatever the worker thread raised (``PeerLost``, ``WorldChanged`` from
    the elastic ``on_wait`` probe, a store ``ConnectionError``), so the
    caller's recovery path sees exactly what the synchronous call would
    have thrown."""

    __slots__ = ("_event", "_result", "_exc")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._result: Any = None
        self._exc: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout_s: float | None = None) -> Any:
        if not self._event.wait(timeout_s):
            raise PeerLost(f"async collective not done within {timeout_s}s")
        if self._exc is not None:
            raise self._exc
        return self._result

    def _finish(self, result: Any = None,
                exc: BaseException | None = None) -> None:
        self._result, self._exc = result, exc
        self._event.set()


class _Prefetcher:
    """Background fetcher over its own store connection: the ring's
    next-chunk wait overlaps the current chunk's local reduction (and the
    all-gather's world-1 fetches stream while earlier chunks are being
    placed).  ``submit`` enqueues a key; the owning collective picks the
    bytes up with ``take`` on ITS thread — the elastic ``on_wait`` probe
    (which may raise ``WorldChanged``) always runs on the collective's
    thread, never here."""

    def __init__(self, base_client: CoordClient,
                 abort: threading.Event) -> None:
        self._client = base_client.clone()
        self._q: queue.Queue = queue.Queue()
        self._res: dict[str, bytes | BaseException] = {}
        self._cond = threading.Condition()
        self._abort = abort
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tpudist-coll-prefetch")
        self._thread.start()

    def submit(self, key: str, deadline: float) -> None:
        self._q.put((key, deadline))

    def _loop(self) -> None:
        try:
            while True:
                item = self._q.get()
                if item is None:
                    return
                key, deadline = item
                try:
                    val: bytes | BaseException = self._fetch(key, deadline)
                except BaseException as e:  # noqa: BLE001 - delivered at take()
                    val = e
                with self._cond:
                    self._res[key] = val
                    self._cond.notify_all()
        finally:
            self._client.close()

    def _fetch(self, key: str, deadline: float) -> bytes:
        while True:
            raw = self._client.get(key)
            if raw is not None:
                return raw
            if self._abort.is_set():
                raise PeerLost(f"collective aborted waiting for {key}")
            if time.monotonic() > deadline:
                raise PeerLost(
                    f"peer never posted {key} within the collective's "
                    f"shared deadline")
            self._client.wait(key, timeout_s=0.2)

    def take(self, key: str, deadline: float,
             on_wait: Callable[[], None] | None) -> bytes:
        with self._cond:
            while key not in self._res:
                if on_wait is not None:
                    on_wait()  # may raise WorldChanged — caller's thread
                if self._abort.is_set():
                    raise PeerLost(f"collective aborted waiting for {key}")
                if time.monotonic() > deadline:
                    raise PeerLost(
                        f"peer never posted {key} within the collective's "
                        f"shared deadline")
                self._cond.wait(0.05)
            val = self._res.pop(key)
        if isinstance(val, BaseException):
            raise val
        return val

    def close(self) -> None:
        self._q.put(None)


# ---------------------------------------------------------------------------


class HostCollectives:
    """Fixed-membership collectives for one rendezvous round.

    Args:
      client: store connection (the caller's thread uses it; background
        workers clone their own — do not share with a concurrently-beating
        monitor, it clones its own too).
      rank / world: this participant's dense rank and the round's size
        (from :meth:`tpudist.runtime.coord.Rendezvous.join_live`).
      round_id: rendezvous round; namespaces all keys so a new round never
        sees a dead round's leftovers.
      on_wait: optional callback invoked between wait polls — the elastic
        hook: pass ``ElasticMonitor.check`` so a TTL-expired peer turns a
        hung allreduce into ``WorldChanged`` instead of a timeout.  Always
        invoked on the thread running the collective (the caller for sync
        ops, the async worker for ``*_async`` ops — re-raised from
        :meth:`Handle.wait`).
      timeout_s: per-collective deadline before :class:`PeerLost`.  The
        deadline is SHARED by every chunk of one collective: a peer dying
        mid-ring surfaces once, after ``timeout_s``, not once per
        remaining chunk.
      config: algorithm/fusion/compression knobs; defaults to
        :meth:`CollectiveConfig.from_env`.

    Threading contract: collectives must be issued from one thread (SPMD
    programs issue them in lockstep anyway).  ``*_async`` submissions are
    executed in submission order on one background worker; a synchronous
    collective first drains the async queue, so operation ids stay agreed
    across ranks.
    """

    def __init__(
        self,
        client: CoordClient,
        rank: int,
        world: int,
        round_id: int = 0,
        namespace: str = "coll",
        on_wait: Callable[[], None] | None = None,
        timeout_s: float = 60.0,
        config: CollectiveConfig | None = None,
    ) -> None:
        self.client = client
        self.rank = rank
        self.world = world
        self.round_id = round_id
        self.ns = namespace
        self.on_wait = on_wait
        self.timeout_s = timeout_s
        self.config = config if config is not None \
            else CollectiveConfig.from_env()
        self._op = 0
        self._posted: dict[int, list[str]] = {}  # op -> keys (for GC)
        self.bytes_posted = 0     # per-instance wire accounting (bench/tests
        self.bytes_fetched = 0    # read these; obs counters are global)
        self._abort = threading.Event()
        self._io: _Prefetcher | None = None        # sync-path prefetcher
        self._async_io: _Prefetcher | None = None  # worker-path prefetcher
        self._async_client: CoordClient | None = None
        self._async_q: queue.Queue | None = None
        self._async_thread: threading.Thread | None = None
        self._pending: list[Handle] = []
        self._closed = False

    # -- keys, GC, raw post/fetch ------------------------------------------

    def _key(self, op: int, rank: int) -> str:
        return f"{self.ns}/{self.round_id}/{op}/{rank}"

    def _ring_key(self, op: int, phase: str, bucket: int,
                  idx: int, rank: int | None = None) -> str:
        tail = f"/{rank}" if rank is not None else ""
        return f"{self.ns}/{self.round_id}/{op}/{phase}/{bucket}/{idx}{tail}"

    def _begin_op(self, client: CoordClient) -> int:
        """Allocate the next operation id and GC everything this rank
        posted for ``op - 2`` (every peer consumed those before posting
        ``op - 1`` — see the module docstring's induction)."""
        op = self._op
        self._op += 1
        for key in self._posted.pop(op - 2, ()):
            client.delete(key)
        return op

    def _post(self, client: CoordClient, op: int, key: str,
              payload: bytes) -> None:
        obs.counter("coll/bytes_posted", unit="bytes").inc(len(payload))
        self.bytes_posted += len(payload)
        client.set(key, payload)
        self._posted.setdefault(op, []).append(key)

    def _account_fetch(self, raw: bytes, waited_s: float) -> bytes:
        obs.counter("coll/bytes_fetched", unit="bytes").inc(len(raw))
        obs.histogram("coll/fetch_wait_s", unit="s").record(waited_s)
        self.bytes_fetched += len(raw)
        return raw

    def _fetch(self, client: CoordClient, key: str, deadline: float,
               on_wait: Callable[[], None] | None) -> bytes:
        """Inline blocking fetch (flat + broadcast paths)."""
        t0 = time.perf_counter()
        while True:
            raw = client.get(key)
            if raw is not None:
                return self._account_fetch(raw, time.perf_counter() - t0)
            if on_wait is not None:
                on_wait()
            if self._abort.is_set():
                raise PeerLost(f"collective aborted waiting for {key}")
            if time.monotonic() > deadline:
                raise PeerLost(
                    f"peer never posted {key} within the collective's "
                    f"shared deadline ({self.timeout_s}s)")
            client.wait(key, timeout_s=0.2)

    def _take(self, io: _Prefetcher, key: str, deadline: float,
              on_wait: Callable[[], None] | None) -> bytes:
        t0 = time.perf_counter()
        raw = io.take(key, deadline, on_wait)
        return self._account_fetch(raw, time.perf_counter() - t0)

    def _peer_order(self) -> list[int]:
        """Every rank's fetch sequence starts at its RIGHT neighbor and
        wraps — rank-identical sequences would hot-spot rank 0's keys on
        the store with ``world`` simultaneous reads (the reduction order
        stays rank-/ring-fixed regardless; only the FETCH order
        staggers)."""
        return [(self.rank + i) % self.world for i in range(1, self.world)]

    # -- allreduce ----------------------------------------------------------

    def allreduce_sum(self, tree: Any) -> Any:
        """Sum a pytree of arrays across all ranks.

        Dispatches on payload size and ``config.algorithm``: a flat
        post-everything/fetch-everyone gather for tiny trees, the chunked
        ring for everything else.  Either way every rank returns a
        bitwise-identical tree (see the module determinism contract) —
        the invariant the elastic grow test's checksum relies on."""
        self._drain_async()
        return self._run_allreduce(
            tree, self.client, on_wait=self.on_wait)

    def allreduce_mean(self, tree: Any) -> Any:
        import jax

        summed = self.allreduce_sum(tree)
        return jax.tree.map(lambda x: x / self.world, summed)

    def allreduce_sum_async(self, tree: Any) -> Handle:
        """Start an allreduce on the background worker; returns a
        :class:`Handle` whose :meth:`~Handle.wait` yields exactly what
        :meth:`allreduce_sum` would have returned (or re-raises the
        worker-side error).  Submissions run in order; a subsequent sync
        collective drains them first, so op ids stay SPMD-agreed."""
        return self._submit("sum", tree)

    def allreduce_mean_async(self, tree: Any) -> Handle:
        return self._submit("mean", tree)

    def _submit(self, kind: str, tree: Any) -> Handle:
        if self._closed:
            raise PeerLost("collectives closed (round over)")
        obs.counter("coll/allreduce_async", unit="calls").inc()
        self._ensure_async_worker()
        handle = Handle()
        self._pending.append(handle)
        assert self._async_q is not None
        self._async_q.put((kind, tree, handle))
        return handle

    def _ensure_async_worker(self) -> None:
        if self._async_thread is None:
            # clone on the caller's thread so connection failures surface
            # here, not silently inside the worker
            self._async_client = self.client.clone()
            self._async_q = queue.Queue()
            self._async_thread = threading.Thread(
                target=self._async_loop, daemon=True,
                name="tpudist-coll-async")
            self._async_thread.start()

    def _async_loop(self) -> None:
        assert self._async_client is not None and self._async_q is not None
        try:
            while True:
                item = self._async_q.get()
                if item is None:
                    return
                kind, tree, handle = item
                try:
                    out = self._run_allreduce(
                        tree, self._async_client, on_wait=self.on_wait,
                        async_path=True)
                    if kind == "mean":
                        import jax

                        out = jax.tree.map(lambda x: x / self.world, out)
                    handle._finish(result=out)
                except BaseException as e:  # noqa: BLE001 - re-raised at wait()
                    handle._finish(exc=e)
        finally:
            self._async_client.close()

    def _drain_async(self) -> None:
        """Wait for every outstanding async collective to complete (their
        errors stay with their handles).  Keeps sync and async ops
        totally ordered, so operation ids agree across ranks."""
        pending, self._pending = self._pending, []
        for h in pending:
            h._event.wait()

    # -- the allreduce engine ----------------------------------------------

    def _run_allreduce(self, tree: Any, client: CoordClient,
                       on_wait: Callable[[], None] | None,
                       async_path: bool = False) -> Any:
        import jax

        obs.counter("coll/allreduce", unit="calls").inc()
        t_start = time.perf_counter()
        leaves, treedef = jax.tree.flatten(tree)
        np_leaves = [np.asarray(x) for x in leaves]
        total_bytes = sum(l.nbytes for l in np_leaves)
        if self.world == 1 or not np_leaves or total_bytes == 0:
            return jax.tree.unflatten(
                treedef, [np.array(l, copy=True) for l in np_leaves])
        buckets, plan = _fuse(np_leaves, self.config)
        wire_bytes = sum(b.wire_nbytes for b in buckets)
        if wire_bytes:
            obs.gauge("coll/compress_ratio").set(total_bytes / wire_bytes)
        algo = self.config.algorithm
        if algo == "auto":
            algo = ("flat" if self.world <= 2
                    or total_bytes <= self.config.flat_max_bytes else "ring")
        op = self._begin_op(client)
        deadline = time.monotonic() + self.timeout_s
        io: _Prefetcher | None = None
        if algo == "ring":
            io = self._prefetcher(async_path)
        reducer = self._ring if algo == "ring" else self._flat
        reduced_buckets = reducer(buckets, op, client, io, deadline, on_wait)
        reduced: dict[str, list[np.ndarray]] = {}
        for b, vec in zip(buckets, reduced_buckets):
            reduced.setdefault(b.group, []).append(vec)
        out = _defuse(reduced, plan, np_leaves)
        obs.histogram("coll/allreduce_s", unit="s").record(
            time.perf_counter() - t_start)
        return jax.tree.unflatten(treedef, out)

    def _prefetcher(self, async_path: bool) -> _Prefetcher:
        if async_path:
            if self._async_io is None:
                assert self._async_client is not None
                self._async_io = _Prefetcher(self._async_client, self._abort)
            return self._async_io
        if self._io is None:
            self._io = _Prefetcher(self.client, self._abort)
        return self._io

    def _flat(self, buckets: list[_Bucket], op: int, client: CoordClient,
              io: _Prefetcher | None, deadline: float,
              on_wait: Callable[[], None] | None) -> list[np.ndarray]:
        """All-gather + rank-ordered local reduce: one posted blob, one
        fetch per peer (staggered start — see :meth:`_peer_order`).  Best
        for tiny trees where ring round-trips dominate; O(world × size)
        fetch bytes otherwise."""
        payload = b"".join(_encode(b.data, b.wire) for b in buckets)
        self._post(client, op, self._key(op, self.rank), payload)
        raws: dict[int, bytes] = {self.rank: payload}
        for r in self._peer_order():
            raws[r] = self._fetch(client, self._key(op, r), deadline, on_wait)
        out: list[np.ndarray] = []
        off = 0
        for b in buckets:
            blen = b.wire_nbytes
            acc: np.ndarray | None = None
            # reduction in RANK ORDER on every participant, over the
            # POSTED (wire-encoded) payloads — own contribution included,
            # so compression rounding is identical on every rank and
            # float non-associativity cannot diverge replicas
            for r in range(self.world):
                contrib = _decode(raws[r][off:off + blen], b.wire, b.accum)
                if acc is None:
                    acc = np.array(contrib, copy=True)
                else:
                    acc += contrib
            out.append(acc if acc is not None
                       else np.empty(0, b.accum))
            off += blen
        return out

    def _ring(self, buckets: list[_Bucket], op: int, client: CoordClient,
              io: _Prefetcher | None, deadline: float,
              on_wait: Callable[[], None] | None) -> list[np.ndarray]:
        """Chunked ring reduce-scatter + star all-gather (see module
        docstring).  The prefetcher keeps the NEXT hop's store wait in
        flight while this hop's chunk is being reduced."""
        assert io is not None
        world, rank = self.world, self.rank
        left = (rank - 1) % world
        own_final = (rank + 1) % world  # chunk this rank finishes
        bounds = [_chunk_bounds(len(b.data), world) for b in buckets]
        # post every bucket's step-0 chunk up front: peers' prefetchers
        # find their first hop immediately, and bucket k+1's ring can
        # absorb store latency while bucket k reduces
        for bi, b in enumerate(buckets):
            lo, hi = bounds[bi][rank]
            self._post(client, op, self._ring_key(op, "rs", bi, 0, rank),
                       _encode(b.data[lo:hi], b.wire))
        out: list[np.ndarray] = []
        for bi, b in enumerate(buckets):
            io.submit(self._ring_key(op, "rs", bi, 0, left), deadline)
            final_enc: bytes | None = None
            acc: np.ndarray | None = None
            for s in range(world - 1):
                if s + 1 < world - 1:
                    # pipeline: next hop's fetch rides the prefetcher
                    # while this hop decodes + reduces
                    io.submit(self._ring_key(op, "rs", bi, s + 1, left),
                              deadline)
                t0 = time.perf_counter()
                raw = self._take(
                    io, self._ring_key(op, "rs", bi, s, left), deadline,
                    on_wait)
                c = (rank - 1 - s) % world
                lo, hi = bounds[bi][c]
                # fp32 (accum-dtype) add of the decoded partial and this
                # rank's own chunk; exactly ONE rank performs each hop,
                # so the per-chunk reduction order is ring-fixed
                acc = _decode(raw, b.wire, b.accum) + b.data[lo:hi]
                if s + 1 < world - 1:
                    self._post(
                        client, op,
                        self._ring_key(op, "rs", bi, s + 1, rank),
                        _encode(acc, b.wire))
                else:
                    final_enc = _encode(acc, b.wire)
                obs.histogram("coll/ring_chunk_s", unit="s").record(
                    time.perf_counter() - t0)
            # all-gather over the store's star topology: post the finished
            # chunk ONCE; every peer fetches the owner's single post (ring
            # forwarding would re-upload each chunk world-2 more times)
            assert final_enc is not None
            self._post(client, op, self._ring_key(op, "ag", bi, own_final),
                       final_enc)
            order = [(own_final + i) % world for i in range(1, world)]
            for c in order:
                io.submit(self._ring_key(op, "ag", bi, c), deadline)
            pieces: dict[int, np.ndarray] = {
                # decode own ENCODED bytes, not the raw accumulator: with
                # compression on, peers decode the posted bf16 — bitwise
                # agreement requires this rank to do the same
                own_final: _decode(final_enc, b.wire, b.accum)}
            for c in order:
                raw = self._take(io, self._ring_key(op, "ag", bi, c),
                                 deadline, on_wait)
                pieces[c] = _decode(raw, b.wire, b.accum)
            vec = np.empty(len(b.data), b.accum)
            for c in range(world):
                lo, hi = bounds[bi][c]
                vec[lo:hi] = pieces[c]
            out.append(vec)
        return out

    # -- broadcast / barrier ------------------------------------------------

    def broadcast(self, tree: Any, root: int = 0) -> Any:
        """Every rank returns root's pytree (``hvd.broadcast_parameters``
        role, `mnist_horovod.py:56` — state agreement after a resize).
        Payload rides uncompressed npz: state agreement must be EXACT,
        unlike gradient sync there is no accumulation to absorb rounding.

        Synchronizing: a trailing barrier guarantees every peer consumed
        the payload before anyone proceeds — without it, the root's op-2
        key GC could delete a broadcast a slow peer hasn't read yet
        (allreduce doesn't need this: posting op N implies having read
        every peer's op N-1)."""
        import jax

        self._drain_async()
        obs.counter("coll/broadcast", unit="calls").inc()
        leaves, treedef = jax.tree.flatten(tree)
        op = self._begin_op(self.client)
        if self.rank == root:
            self._post(self.client, op, self._key(op, root),
                       _dumps([np.asarray(x) for x in leaves]))
            out_tree = tree
        else:
            deadline = time.monotonic() + self.timeout_s
            out_tree = jax.tree.unflatten(
                treedef, _loads(self._fetch(
                    self.client, self._key(op, root), deadline,
                    self.on_wait)))
        self.barrier()
        return out_tree

    def barrier(self, timeout_s: float | None = None) -> None:
        """All-ranks barrier for this round (native store barrier)."""
        self._drain_async()
        obs.counter("coll/barrier", unit="calls").inc()
        op = self._begin_op(self.client)
        ok = self.client.barrier(
            f"{self.ns}/{self.round_id}/bar/{op}", self.world,
            timeout_s or self.timeout_s)
        if not ok:
            raise PeerLost(f"barrier {op} timed out at world {self.world}")

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Stop background workers (async executor + prefetchers) and
        abort any in-flight waits with :class:`PeerLost`.  Idempotent;
        does NOT close the caller-owned ``client``."""
        if self._closed:
            return
        self._closed = True
        self._abort.set()
        if self._async_q is not None:
            self._async_q.put(None)
        for io in (self._io, self._async_io):
            if io is not None:
                io.close()

    def close_round(self) -> None:
        """Delete every key this round left in the store (called before
        re-rendezvous so dead rounds cannot accumulate; idempotent —
        every survivor may call it).  Also tears down this instance's
        background workers: a dead round's async op must not keep
        fetching."""
        self.close()
        for key in self.client.keys(f"{self.ns}/{self.round_id}/"):
            try:
                self.client.delete(key)
            except ConnectionError:
                return
