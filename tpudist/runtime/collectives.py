"""Host-side collectives over the native coordination store.

The reference's elastic paths lean on CPU collective backends — Gloo for DDP
(`mnist_ddp_elastic.py:26`) and Horovod's controller for the elastic driver
(`horovod_mnist_elastic.py:35,55`) — whose defining property is that the
*membership* of a collective is renegotiable at run time.  XLA's ICI
collectives (the tpudist data plane) are compiled for a fixed mesh; this
module provides the complementary control-plane collectives with DYNAMIC
membership, built on the C++ TCP store (``native/coord.cpp``): allreduce /
broadcast / barrier whose participant set is whatever the current rendezvous
round agreed on.

That property is what makes in-process elastic resize possible
(:mod:`tpudist.elastic.worker`): when a worker dies mid-allreduce, the
surviving participants' waits time out against the TTL-expired live set and
surface :class:`~tpudist.elastic.loop.WorldChanged` — they re-rendezvous
smaller and the next round's collectives simply have fewer participants.
No compiled program needs to change, because these collectives live outside
XLA.

Wire format: each participant posts its payload under
``{ns}/{round}/{op}/{rank}`` and reads every peer's key.  Values are npz
bytes (dtype/shape-preserving) of the flattened pytree leaves.  A
participant deletes its own ``op - 2`` key when posting ``op`` — by then
every peer has consumed it (they must have completed ``op - 1`` to be
posting/reading ``op``), so the store stays O(2 · world) keys per round.
"""

from __future__ import annotations

import io
import time
from typing import Any, Callable

import numpy as np

from tpudist import obs
from tpudist.runtime.coord import CoordClient


class PeerLost(RuntimeError):
    """A collective wait exceeded its deadline; membership likely changed."""


def _dumps(leaves: list[np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, *leaves)
    return buf.getvalue()


def _loads(raw: bytes) -> list[np.ndarray]:
    with np.load(io.BytesIO(raw)) as z:
        # index by position, not z.files order (lexicographic would put
        # arr_10 before arr_2)
        return [z[f"arr_{i}"] for i in range(len(z.files))]


class HostCollectives:
    """Fixed-membership collectives for one rendezvous round.

    Args:
      client: store connection (one in-flight request per connection; do
        not share with a concurrently-beating monitor — it clones its own).
      rank / world: this participant's dense rank and the round's size
        (from :meth:`tpudist.runtime.coord.Rendezvous.join_live`).
      round_id: rendezvous round; namespaces all keys so a new round never
        sees a dead round's leftovers.
      on_wait: optional callback invoked between wait polls — the elastic
        hook: pass ``ElasticMonitor.check`` so a TTL-expired peer turns a
        hung allreduce into ``WorldChanged`` instead of a timeout.
      timeout_s: per-collective deadline before :class:`PeerLost`.
    """

    def __init__(
        self,
        client: CoordClient,
        rank: int,
        world: int,
        round_id: int = 0,
        namespace: str = "coll",
        on_wait: Callable[[], None] | None = None,
        timeout_s: float = 60.0,
    ) -> None:
        self.client = client
        self.rank = rank
        self.world = world
        self.round_id = round_id
        self.ns = namespace
        self.on_wait = on_wait
        self.timeout_s = timeout_s
        self._op = 0

    def _key(self, op: int, rank: int) -> str:
        return f"{self.ns}/{self.round_id}/{op}/{rank}"

    def _post(self, payload: bytes) -> int:
        op = self._op
        self._op += 1
        obs.counter("coll/bytes_posted", unit="bytes").inc(len(payload))
        self.client.set(self._key(op, self.rank), payload)
        if op >= 2:  # every peer consumed op-2 before posting op-1
            self.client.delete(self._key(op - 2, self.rank))
        return op

    def _fetch(self, op: int, rank: int) -> bytes:
        deadline = time.monotonic() + self.timeout_s
        key = self._key(op, rank)
        while True:
            raw = self.client.get(key)
            if raw is not None:
                return raw
            if self.on_wait is not None:
                self.on_wait()
            if time.monotonic() > deadline:
                raise PeerLost(
                    f"rank {rank} never posted {key} within "
                    f"{self.timeout_s}s")
            self.client.wait(key, timeout_s=0.2)

    def allreduce_sum(self, tree: Any) -> Any:
        """Sum a pytree of arrays across all ranks (all-gather + local
        reduce; payloads ride the store, O(world) per rank).

        The reduction runs in RANK ORDER on every participant — float
        addition is non-associative, so a per-rank order (e.g. own shard
        first) would leave replicas differing in ULPs and silently
        diverging over steps (caught by the elastic grow test's bitwise
        checksum)."""
        import jax

        obs.counter("coll/allreduce", unit="calls").inc()
        leaves, treedef = jax.tree.flatten(tree)
        np_leaves = [np.asarray(x) for x in leaves]
        op = self._post(_dumps(np_leaves))
        acc: list[np.ndarray] | None = None
        for r in range(self.world):
            contrib = (np_leaves if r == self.rank
                       else _loads(self._fetch(op, r)))
            if acc is None:
                acc = [np.array(c, copy=True) for c in contrib]
            else:
                for a, b in zip(acc, contrib):
                    a += b
        return jax.tree.unflatten(treedef, acc)

    def allreduce_mean(self, tree: Any) -> Any:
        import jax

        summed = self.allreduce_sum(tree)
        return jax.tree.map(lambda x: x / self.world, summed)

    def broadcast(self, tree: Any, root: int = 0) -> Any:
        """Every rank returns root's pytree (``hvd.broadcast_parameters``
        role, `mnist_horovod.py:56` — state agreement after a resize).

        Synchronizing: a trailing barrier guarantees every peer consumed
        the payload before anyone proceeds — without it, the root's op-2
        key GC could delete a broadcast a slow peer hasn't read yet
        (allreduce doesn't need this: posting op N implies having read
        every peer's op N-1)."""
        import jax

        obs.counter("coll/broadcast", unit="calls").inc()
        leaves, treedef = jax.tree.flatten(tree)
        if self.rank == root:
            self._post(_dumps([np.asarray(x) for x in leaves]))
            out_tree = tree
        else:
            op = self._op
            self._op += 1
            out_tree = jax.tree.unflatten(
                treedef, _loads(self._fetch(op, root)))
        self.barrier()
        return out_tree

    def barrier(self, timeout_s: float | None = None) -> None:
        """All-ranks barrier for this round (native store barrier)."""
        obs.counter("coll/barrier", unit="calls").inc()
        op = self._op
        self._op += 1
        ok = self.client.barrier(
            f"{self.ns}/{self.round_id}/bar/{op}", self.world,
            timeout_s or self.timeout_s)
        if not ok:
            raise PeerLost(f"barrier {op} timed out at world {self.world}")

    def close_round(self) -> None:
        """Delete every key this round left in the store (called before
        re-rendezvous so dead rounds cannot accumulate; idempotent —
        every survivor may call it)."""
        for key in self.client.keys(f"{self.ns}/{self.round_id}/"):
            try:
                self.client.delete(key)
            except ConnectionError:
                return
