"""Host-side collectives over the native coordination store.

The reference's elastic paths lean on CPU collective backends — Gloo for DDP
(`mnist_ddp_elastic.py:26`) and Horovod's controller for the elastic driver
(`horovod_mnist_elastic.py:35,55`) — whose defining property is that the
*membership* of a collective is renegotiable at run time.  XLA's ICI
collectives (the tpudist data plane) are compiled for a fixed mesh; this
module provides the complementary control-plane collectives with DYNAMIC
membership, built on the C++ TCP store (``native/coord.cpp``): allreduce /
broadcast / barrier whose participant set is whatever the current rendezvous
round agreed on.

That property is what makes in-process elastic resize possible
(:mod:`tpudist.elastic.worker`): when a worker dies mid-allreduce, the
surviving participants' waits time out against the TTL-expired live set and
surface :class:`~tpudist.elastic.loop.WorldChanged` — they re-rendezvous
smaller and the next round's collectives simply have fewer participants.
No compiled program needs to change, because these collectives live outside
XLA.

Allreduce is BANDWIDTH-OPTIMAL, not naive (the Horovod-core trio the
reference gets for free from ``hvd.DistributedOptimizer``,
`mnist_horovod.py:53`):

* **bucket fusion** — the pytree is flattened into fused flat buffers of
  at most ``bucket_bytes`` (leaves grouped by dtype, concatenated in a
  deterministic order), so wire cost is per-bucket, not per-leaf;
* **ring reduce-scatter** — each fused bucket is split into ``world``
  chunks; chunk ``c`` travels the ring ``c → c+1 → … → c-1``, each hop
  adding its own contribution, so every rank uploads/downloads
  ``(world-1)/world`` of the payload instead of ``world×`` of it.  The
  all-gather half uses the store's star topology directly: the rank that
  finishes chunk ``c`` posts it ONCE and every peer fetches it — ring
  forwarding would double store traffic for nothing.  Net wire bytes per
  rank: ``2·(world−1)/world × size`` fetched (vs ``(world−1) × size``
  flat), ``~1 × size`` posted;
* **wire compression** — float32 payloads optionally travel as bf16
  (default) or fp16 with float32 accumulation at every hop
  (``coll/compress_ratio`` reports the saving); every other dtype rides
  raw;
* **hierarchical topology** (``algorithm="hier"``) — ranks are grouped
  into ``hosts`` contiguous host groups; each bucket is reduce-scattered
  WITHIN the host (over the injected ICI plane, or store-simulated in
  lossless accum-dtype bytes), then each shard runs the chunked ring
  across ONE representative rank per host, then the finished shards
  all-gather back within the host.  Cross-host wire bytes drop from
  ``2·(world−1)/world × size`` per RANK to ``2·(H−1)/H × size`` per
  HOST (H = host count) — the bytes the slow DCN link actually carries;
* **top-k sparsification** (``compress="topk"``) — every lossy message
  keeps only the ``topk_frac`` largest-magnitude elements (int32 index +
  f32 value per survivor, so wire ≈ ``2·frac ×`` dense), re-sparsified
  at every ring hop; what an encode drops is accumulated in a per-bucket
  ERROR-FEEDBACK residual owned by :class:`HostCollectives` and folded
  into this rank's next contribution, so dropped mass is delayed, never
  lost.  Residuals die with the instance — one instance per rendezvous
  round means a membership change drops them rather than replaying them
  into a differently-shaped world;
* **async overlap** — :meth:`HostCollectives.allreduce_sum_async` returns
  a :class:`Handle` and runs post/fetch/reduce on a background worker, so
  the caller's next microbatch overlaps the previous one's wire time
  (``hvd.DistributedOptimizer`` semantics — see
  :class:`tpudist.elastic.worker.OverlappedGradSync`).

DETERMINISM CONTRACT: after any allreduce, every participant holds a
bitwise-identical result.  Ring: each chunk is reduced exactly once, by one
rank per hop in fixed ring order, and the finished chunk's *encoded bytes*
are what every rank decodes — no rank re-does a reduction another rank
already did.  Flat: every rank reduces in rank order over the *posted*
(wire-encoded) payloads, including its own, so compression rounding is
identical everywhere.  Hier: intra-host reduction runs in fixed
local-rank order, each cross-host shard ring is ring-fixed, and the
intra all-gather re-posts the (identical) decoded shard bytes raw.  The
algorithms may differ from each other in ULPs (different addition
order), and topk additionally drops mass into residuals — but for every
algorithm × compression combination, replicas never differ from each
other.

Wire format: flat posts one fused blob per rank under
``{ns}/{round}/{op}/{rank}``; ring posts chunk partials under
``{ns}/{round}/{op}/rs/{bucket}/{step}/{rank}`` and finished chunks under
``{ns}/{round}/{op}/ag/{bucket}/{chunk}``; hier adds intra-host posts
under ``.../hrs/{bucket}/{dest}/{src}`` + ``.../hag/{bucket}/{owner}``
and per-shard cross-host rings under ``.../xrs/{bucket}.{shard}/...`` +
``.../xag/{bucket}.{shard}/...``.  Chunk payloads are raw bytes of
the wire dtype — both sides derive shapes/offsets from the (identical)
fusion plan, so no per-message header is needed.  Key GC: a participant
deletes every key it posted for ``op - 2`` when starting ``op`` — by then
every peer has consumed them (finishing ``op`` requires the whole ring to
have finished ``op - 1``), so the store stays O(ring keys × 2) per round.
"""

from __future__ import annotations

import dataclasses
import io
import os
import queue
import threading
import time
from typing import Any, Callable

import numpy as np

from tpudist import obs
from tpudist.runtime.coord import CoordClient


class PeerLost(RuntimeError):
    """A collective wait exceeded its deadline; membership likely changed."""


# ---------------------------------------------------------------------------
# configuration


ALGORITHMS = ("auto", "flat", "ring", "hier")
COMPRESSIONS = ("none", "bf16", "fp16", "topk")


@dataclasses.dataclass(frozen=True)
class CollectiveConfig:
    """Knobs for the host allreduce (Horovod's fusion-buffer/compression
    trio, `HOROVOD_FUSION_THRESHOLD` analog).

    * ``algorithm`` — ``auto`` (flat for tiny payloads or ``world <= 2``,
      ring otherwise), or force ``flat`` / ``ring`` / ``hier``
      (hierarchical: intra-host reduce-scatter, cross-host ring over one
      representative rank per host, intra-host all-gather — needs
      ``hosts`` to divide the world, falls back to ``ring`` otherwise).
    * ``bucket_bytes`` — fused-buffer cap; one ring runs per bucket, so
      smaller buckets start their wire time earlier but cost more store
      round-trips.
    * ``compress`` — ``bf16`` (default) / ``fp16`` / ``none`` / ``topk``
      (top-k magnitude sparsification with error-feedback residuals);
      applies to float32 payloads only, accumulation stays float32.
      Under ``hier`` compression applies to the CROSS-HOST wire; the
      intra-host phases ride lossless accumulation-dtype bytes.
    * ``flat_max_bytes`` — ``auto`` switches to ring above this payload
      size (the flat gather's one-post/one-fetch-per-peer latency beats
      the ring's ``2·world`` round-trips for small trees).
    * ``topk_frac`` — fraction of each compressed message's elements kept
      by ``topk`` (wire bytes ≈ ``2·frac`` of dense: int32 index + f32
      value per survivor).
    * ``hosts`` — host-group count for ``hier``; ranks are grouped
      contiguously (host = ``rank // (world/hosts)``), matching how the
      launcher numbers a gang.

    Every field has a ``TPUDIST_COLL_*`` environment override (read by
    :meth:`from_env`, the default for :class:`HostCollectives`), so the
    elastic worker and the launcher-spawned gang pick the same plan
    without plumbing.
    """

    algorithm: str = "auto"
    bucket_bytes: int = 4 << 20
    compress: str = "bf16"
    flat_max_bytes: int = 64 << 10
    topk_frac: float = 0.25
    hosts: int = 1

    def __post_init__(self) -> None:
        self.validate()

    def validate(self) -> None:
        """Reject out-of-range knobs with the allowed values in the
        message — a typo'd ``TPUDIST_COLL_*`` override must fail at
        config construction, not surface as dispatch-time weirdness."""
        if self.algorithm not in ALGORITHMS:
            raise ValueError(
                f"unknown algorithm {self.algorithm!r} "
                f"(TPUDIST_COLL_ALGO); allowed: {', '.join(ALGORITHMS)}")
        if self.compress not in COMPRESSIONS:
            raise ValueError(
                f"unknown compress {self.compress!r} "
                f"(TPUDIST_COLL_COMPRESS); allowed: "
                f"{', '.join(COMPRESSIONS)}")
        if self.bucket_bytes < 64:
            raise ValueError(f"bucket_bytes too small: {self.bucket_bytes}")
        if not 0.0 < self.topk_frac <= 1.0:
            raise ValueError(
                f"topk_frac must be in (0, 1], got {self.topk_frac} "
                f"(TPUDIST_COLL_TOPK_FRAC)")
        if self.hosts < 1:
            raise ValueError(
                f"hosts must be >= 1, got {self.hosts} "
                f"(TPUDIST_COLL_HOSTS)")

    @classmethod
    def from_env(cls) -> "CollectiveConfig":
        return cls(
            algorithm=os.environ.get("TPUDIST_COLL_ALGO", cls.algorithm),
            bucket_bytes=int(os.environ.get("TPUDIST_COLL_BUCKET_BYTES",
                                            cls.bucket_bytes)),
            compress=os.environ.get("TPUDIST_COLL_COMPRESS", cls.compress),
            flat_max_bytes=int(os.environ.get("TPUDIST_COLL_FLAT_MAX_BYTES",
                                              cls.flat_max_bytes)),
            topk_frac=float(os.environ.get("TPUDIST_COLL_TOPK_FRAC",
                                           cls.topk_frac)),
            hosts=int(os.environ.get("TPUDIST_COLL_HOSTS", cls.hosts)),
        )


# ---------------------------------------------------------------------------
# wire codecs + bucket fusion


def _bf16() -> np.dtype:
    import ml_dtypes  # jax dependency, always present with jax

    return np.dtype(ml_dtypes.bfloat16)


def _wire_dtype(native: np.dtype, compress: str) -> np.dtype:
    """The dtype a group's bytes travel as: float32 compresses to
    bf16/fp16 when asked; everything else (ints, bool, f64, and already-
    half floats) rides raw.  ``topk`` payloads are sparse, not a dtype
    cast — their wire dtype stays the native one and the sparse codec
    below owns the byte layout."""
    if native == np.float32 and compress in ("bf16", "fp16"):
        return _bf16() if compress == "bf16" else np.dtype(np.float16)
    return native


def _topk_k(n: int, frac: float) -> int:
    """Survivor count for a ``topk`` message of ``n`` elements: fixed
    and derived from the (identical) config on every rank, so message
    sizes need no header and byte accounting is exact."""
    if n == 0:
        return 0
    return max(1, min(n, int(np.ceil(n * frac))))


def _encode_topk(arr: np.ndarray, frac: float) -> bytes:
    """Top-k magnitude sparsification: keep the ``k`` largest-|x|
    elements, wire format ``k × int32 index (sorted ascending) + k ×
    f32 value``.  Sorted indices make the encoding a pure function of
    the input values, so every rank re-encoding the same array posts
    the same bytes."""
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    n = arr.size
    k = _topk_k(n, frac)
    if k >= n:
        idx = np.arange(n, dtype=np.int32)
    else:
        idx = np.sort(np.argpartition(np.abs(arr), n - k)[n - k:]) \
            .astype(np.int32)
    return idx.tobytes() + arr[idx].tobytes()


def _decode_topk(raw: bytes, n: int) -> np.ndarray:
    """Densify a topk payload back to length ``n`` (zeros off-support).
    ``k`` is implied by the payload length — both halves are 4 bytes per
    survivor."""
    k = len(raw) // 8
    out = np.zeros(n, dtype=np.float32)
    if k:
        idx = np.frombuffer(raw[:4 * k], dtype=np.int32)
        out[idx] = np.frombuffer(raw[4 * k:], dtype=np.float32)
    return out


def _accum_dtype(native: np.dtype) -> np.dtype:
    """float32 accumulation for <= 16-bit floats (the fp32-master-copy
    rule of mixed-precision allreduce); everything else accumulates in
    its own dtype (ints must stay exact, f64 must not narrow)."""
    if native == np.float16 or native == _bf16():
        return np.dtype(np.float32)
    return native


def _encode(arr: np.ndarray, wire: np.dtype) -> bytes:
    return np.ascontiguousarray(arr.astype(wire, copy=False)).tobytes()


def _decode(raw: bytes, wire: np.dtype, accum: np.dtype) -> np.ndarray:
    # frombuffer is zero-copy (read-only); the astype to the accumulation
    # dtype copies exactly when it has to
    return np.frombuffer(raw, dtype=wire).astype(accum, copy=False)


@dataclasses.dataclass
class _Bucket:
    group: str            # dtype token of the owning group
    data: np.ndarray      # 1-D accum-dtype slice of the group's fused vector
    wire: np.dtype
    accum: np.dtype
    # the compression slot: ``frac`` set means this bucket's lossy
    # messages ride the sparse topk codec instead of a dtype cast;
    # ``residual`` (when error feedback is live) collects what each of
    # THIS rank's encodes dropped, at bucket-global offsets
    frac: float | None = None
    residual: np.ndarray | None = None

    @property
    def wire_nbytes(self) -> int:
        if self.frac is not None:
            return 8 * _topk_k(len(self.data), self.frac)
        return len(self.data) * self.wire.itemsize


def _bucket_encode(b: _Bucket, arr: np.ndarray, off: int = 0) -> bytes:
    """Encode one wire message for bucket ``b`` (``arr`` lives at
    bucket-global offset ``off``).  For topk buckets the encode is where
    gradient mass is lost, so the error-feedback residual is written
    HERE: each region a rank encodes gets its drop recorded exactly once
    per op (ring/hier topology guarantees the regions don't overlap),
    and the next op re-injects it."""
    if b.frac is None:
        return _encode(arr, b.wire)
    raw = _encode_topk(arr, b.frac)
    if b.residual is not None and arr.size:
        b.residual[off:off + arr.size] = arr - _decode_topk(raw, arr.size)
    return raw


def _bucket_decode(b: _Bucket, raw: bytes, n: int) -> np.ndarray:
    if b.frac is None:
        return _decode(raw, b.wire, b.accum)
    return _decode_topk(raw, n)


def _fuse(np_leaves: list[np.ndarray],
          cfg: CollectiveConfig) -> tuple[list[_Bucket], dict]:
    """Horovod-style tensor fusion: group leaves by dtype (sorted dtype
    token, leaf order within a group — deterministic on every rank),
    concatenate each group into one flat accumulation-dtype vector, and
    slice it into buckets of at most ``bucket_bytes`` wire bytes.

    Returns ``(buckets, plan)`` where ``plan`` maps group token ->
    ``(leaf_indices, native_dtype, group_vectors)`` for :func:`_defuse`.
    """
    groups: dict[str, list[int]] = {}
    for i, leaf in enumerate(np_leaves):
        groups.setdefault(leaf.dtype.str, []).append(i)
    buckets: list[_Bucket] = []
    plan: dict[str, tuple] = {}
    for token in sorted(groups):
        idxs = groups[token]
        native = np.dtype(token)
        accum = _accum_dtype(native)
        wire = _wire_dtype(native, cfg.compress)
        parts = [np_leaves[i].ravel() for i in idxs]
        fused = (np.concatenate(parts) if len(parts) > 1
                 else parts[0]).astype(accum, copy=False)
        frac = (cfg.topk_frac if cfg.compress == "topk"
                and native == np.float32 else None)
        per_bucket = max(1, cfg.bucket_bytes // wire.itemsize)
        group_buckets = [
            _Bucket(token, fused[lo:lo + per_bucket], wire, accum, frac)
            for lo in range(0, len(fused), per_bucket)
        ] or ([_Bucket(token, fused, wire, accum, frac)]
              if fused.size == 0 else [])
        buckets.extend(b for b in group_buckets if b.data.size)
        plan[token] = (idxs, native)
    return buckets, plan


def _defuse(reduced: dict[str, list[np.ndarray]], plan: dict,
            np_leaves: list[np.ndarray]) -> list[np.ndarray]:
    """Inverse of :func:`_fuse`: concatenate each group's reduced bucket
    vectors, cast back to the native dtype, and split into leaf shapes."""
    out: list[np.ndarray | None] = [None] * len(np_leaves)
    for token, (idxs, native) in plan.items():
        vecs = reduced.get(token, [])
        vec = (np.concatenate(vecs) if len(vecs) > 1
               else vecs[0] if vecs
               else np.empty(0, _accum_dtype(native)))
        vec = vec.astype(native, copy=False)
        off = 0
        for i in idxs:
            n = np_leaves[i].size
            out[i] = vec[off:off + n].reshape(np_leaves[i].shape)
            off += n
    return out  # type: ignore[return-value]


def _chunk_bounds(n: int, world: int) -> list[tuple[int, int]]:
    """Ring chunk boundaries: ``world`` near-equal slices of ``[0, n)``
    (first ``n % world`` chunks one element larger) — identical on every
    rank, tolerating ``n < world`` via empty chunks."""
    base, rem = divmod(n, world)
    bounds, lo = [], 0
    for c in range(world):
        hi = lo + base + (1 if c < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def _dumps(leaves: list[np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, *leaves)
    return buf.getvalue()


def _loads(raw: bytes) -> list[np.ndarray]:
    with np.load(io.BytesIO(raw)) as z:
        # index by position, not z.files order (lexicographic would put
        # arr_10 before arr_2)
        return [z[f"arr_{i}"] for i in range(len(z.files))]


# ---------------------------------------------------------------------------
# async plumbing


class Handle:
    """Result of an async collective: :meth:`wait` blocks until the
    background worker finishes and returns the reduced tree — or re-raises
    whatever the worker thread raised (``PeerLost``, ``WorldChanged`` from
    the elastic ``on_wait`` probe, a store ``ConnectionError``), so the
    caller's recovery path sees exactly what the synchronous call would
    have thrown."""

    __slots__ = ("_event", "_result", "_exc")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._result: Any = None
        self._exc: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout_s: float | None = None) -> Any:
        if not self._event.wait(timeout_s):
            raise PeerLost(f"async collective not done within {timeout_s}s")
        if self._exc is not None:
            raise self._exc
        return self._result

    def _finish(self, result: Any = None,
                exc: BaseException | None = None) -> None:
        self._result, self._exc = result, exc
        self._event.set()


class _Prefetcher:
    """Background fetcher over its own store connection: the ring's
    next-chunk wait overlaps the current chunk's local reduction (and the
    all-gather's world-1 fetches stream while earlier chunks are being
    placed).  ``submit`` enqueues a key; the owning collective picks the
    bytes up with ``take`` on ITS thread — the elastic ``on_wait`` probe
    (which may raise ``WorldChanged``) always runs on the collective's
    thread, never here."""

    def __init__(self, base_client: CoordClient,
                 abort: threading.Event) -> None:
        self._client = base_client.clone()
        self._q: queue.Queue = queue.Queue()
        self._res: dict[str, bytes | BaseException] = {}
        self._cond = threading.Condition()
        self._abort = abort
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tpudist-coll-prefetch")
        self._thread.start()

    def submit(self, key: str, deadline: float) -> None:
        self._q.put((key, deadline))

    def _loop(self) -> None:
        try:
            while True:
                item = self._q.get()
                if item is None:
                    return
                key, deadline = item
                try:
                    val: bytes | BaseException = self._fetch(key, deadline)
                except BaseException as e:  # noqa: BLE001 - delivered at take()
                    val = e
                with self._cond:
                    self._res[key] = val
                    self._cond.notify_all()
        finally:
            self._client.close()

    def _fetch(self, key: str, deadline: float) -> bytes:
        while True:
            raw = self._client.get(key)
            if raw is not None:
                return raw
            if self._abort.is_set():
                raise PeerLost(f"collective aborted waiting for {key}")
            if time.monotonic() > deadline:
                raise PeerLost(
                    f"peer never posted {key} within the collective's "
                    f"shared deadline")
            self._client.wait(key, timeout_s=0.2)

    def take(self, key: str, deadline: float,
             on_wait: Callable[[], None] | None) -> bytes:
        with self._cond:
            while key not in self._res:
                if on_wait is not None:
                    on_wait()  # may raise WorldChanged — caller's thread
                if self._abort.is_set():
                    raise PeerLost(f"collective aborted waiting for {key}")
                if time.monotonic() > deadline:
                    raise PeerLost(
                        f"peer never posted {key} within the collective's "
                        f"shared deadline")
                self._cond.wait(0.05)
            val = self._res.pop(key)
        if isinstance(val, BaseException):
            raise val
        return val

    def close(self) -> None:
        self._q.put(None)


# ---------------------------------------------------------------------------


class HostCollectives:
    """Fixed-membership collectives for one rendezvous round.

    Args:
      client: store connection (the caller's thread uses it; background
        workers clone their own — do not share with a concurrently-beating
        monitor, it clones its own too).
      rank / world: this participant's dense rank and the round's size
        (from :meth:`tpudist.runtime.coord.Rendezvous.join_live`).
      round_id: rendezvous round; namespaces all keys so a new round never
        sees a dead round's leftovers.
      on_wait: optional callback invoked between wait polls — the elastic
        hook: pass ``ElasticMonitor.check`` so a TTL-expired peer turns a
        hung allreduce into ``WorldChanged`` instead of a timeout.  Always
        invoked on the thread running the collective (the caller for sync
        ops, the async worker for ``*_async`` ops — re-raised from
        :meth:`Handle.wait`).
      timeout_s: per-collective deadline before :class:`PeerLost`.  The
        deadline is SHARED by every chunk of one collective: a peer dying
        mid-ring surfaces once, after ``timeout_s``, not once per
        remaining chunk.  For ``hier`` the SAME deadline covers all
        three phases — a rank dying between the intra-host and
        cross-host phases still surfaces within one ``timeout_s``.
      config: algorithm/fusion/compression knobs; defaults to
        :meth:`CollectiveConfig.from_env`.
      intra: optional intra-host plane for ``algorithm="hier"`` (e.g.
        :class:`tpudist.runtime.ici.IciIntraHost` wrapping the host's
        ICI mesh).  Must expose ``local_world`` / ``local_index`` /
        ``bounds(n)`` / ``reduce_scatter(vec)`` / ``all_gather(shard,
        n)`` and span exactly this rank's host group (``local_world ==
        world // config.hosts``).  When absent, the intra phases ride
        the coord store (the simulated-ICI path the tests and bench
        drive) — lossless accumulation-dtype bytes either way.

    Error-feedback state (``compress="topk"``) is OWNED by the instance
    and keyed by bucket: dropped gradient mass re-enters this rank's
    next contribution.  A membership change means a new instance per
    round (the elastic worker's structure), so residuals are DROPPED —
    never replayed into a world they weren't accumulated against.

    Threading contract: collectives must be issued from one thread (SPMD
    programs issue them in lockstep anyway).  ``*_async`` submissions are
    executed in submission order on one background worker; a synchronous
    collective first drains the async queue, so operation ids stay agreed
    across ranks.
    """

    def __init__(
        self,
        client: CoordClient,
        rank: int,
        world: int,
        round_id: int = 0,
        namespace: str = "coll",
        on_wait: Callable[[], None] | None = None,
        timeout_s: float = 60.0,
        config: CollectiveConfig | None = None,
        intra: Any | None = None,
    ) -> None:
        self.client = client
        self.rank = rank
        self.world = world
        self.round_id = round_id
        self.ns = namespace
        self.on_wait = on_wait
        self.timeout_s = timeout_s
        self.config = config if config is not None \
            else CollectiveConfig.from_env()
        self.intra = intra
        self._op = 0
        self._posted: dict[int, list[str]] = {}  # op -> keys (for GC)
        self.bytes_posted = 0     # per-instance wire accounting (bench/tests
        self.bytes_fetched = 0    # read these; obs counters are global)
        self.bytes_posted_cross = 0   # hier: cross-host ring bytes only —
        self.bytes_fetched_cross = 0  # the wire the 2(H-1)/H bound is about
        self._in_cross = False
        # topk error feedback: (bucket index, length) -> residual vector;
        # replaced wholesale each op, so a tree-structure change simply
        # starts fresh, and instance-per-round drops it on resize
        self._residuals: dict[tuple[int, int], np.ndarray] = {}
        self._abort = threading.Event()
        self._io: _Prefetcher | None = None        # sync-path prefetcher
        self._async_io: _Prefetcher | None = None  # worker-path prefetcher
        self._async_client: CoordClient | None = None
        self._async_q: queue.Queue | None = None
        self._async_thread: threading.Thread | None = None
        self._pending: list[Handle] = []
        self._closed = False

    # -- keys, GC, raw post/fetch ------------------------------------------

    def _key(self, op: int, rank: int) -> str:
        return f"{self.ns}/{self.round_id}/{op}/{rank}"

    def _ring_key(self, op: int, phase: str, bucket: int,
                  idx: int, rank: int | None = None) -> str:
        tail = f"/{rank}" if rank is not None else ""
        return f"{self.ns}/{self.round_id}/{op}/{phase}/{bucket}/{idx}{tail}"

    def _begin_op(self, client: CoordClient) -> int:
        """Allocate the next operation id and GC everything this rank
        posted for ``op - 2`` (every peer consumed those before posting
        ``op - 1`` — see the module docstring's induction)."""
        op = self._op
        self._op += 1
        for key in self._posted.pop(op - 2, ()):
            client.delete(key)
        return op

    def _post(self, client: CoordClient, op: int, key: str,
              payload: bytes) -> None:
        obs.counter("coll/bytes_posted", unit="bytes").inc(len(payload))
        self.bytes_posted += len(payload)
        if self._in_cross:
            self.bytes_posted_cross += len(payload)
        client.set(key, payload)
        self._posted.setdefault(op, []).append(key)

    def _account_fetch(self, raw: bytes, waited_s: float) -> bytes:
        obs.counter("coll/bytes_fetched", unit="bytes").inc(len(raw))
        obs.histogram("coll/fetch_wait_s", unit="s").record(waited_s)
        self.bytes_fetched += len(raw)
        if self._in_cross:
            self.bytes_fetched_cross += len(raw)
        return raw

    def _fetch(self, client: CoordClient, key: str, deadline: float,
               on_wait: Callable[[], None] | None) -> bytes:
        """Inline blocking fetch (flat + broadcast paths)."""
        t0 = time.perf_counter()
        while True:
            raw = client.get(key)
            if raw is not None:
                return self._account_fetch(raw, time.perf_counter() - t0)
            if on_wait is not None:
                on_wait()
            if self._abort.is_set():
                raise PeerLost(f"collective aborted waiting for {key}")
            if time.monotonic() > deadline:
                raise PeerLost(
                    f"peer never posted {key} within the collective's "
                    f"shared deadline ({self.timeout_s}s)")
            client.wait(key, timeout_s=0.2)

    def _take(self, io: _Prefetcher, key: str, deadline: float,
              on_wait: Callable[[], None] | None) -> bytes:
        t0 = time.perf_counter()
        raw = io.take(key, deadline, on_wait)
        return self._account_fetch(raw, time.perf_counter() - t0)

    def _peer_order(self) -> list[int]:
        """Every rank's fetch sequence starts at its RIGHT neighbor and
        wraps — rank-identical sequences would hot-spot rank 0's keys on
        the store with ``world`` simultaneous reads (the reduction order
        stays rank-/ring-fixed regardless; only the FETCH order
        staggers)."""
        return [(self.rank + i) % self.world for i in range(1, self.world)]

    # -- allreduce ----------------------------------------------------------

    def allreduce_sum(self, tree: Any) -> Any:
        """Sum a pytree of arrays across all ranks.

        Dispatches on payload size and ``config.algorithm``: a flat
        post-everything/fetch-everyone gather for tiny trees, the chunked
        ring for everything else.  Either way every rank returns a
        bitwise-identical tree (see the module determinism contract) —
        the invariant the elastic grow test's checksum relies on."""
        self._drain_async()
        return self._run_allreduce(
            tree, self.client, on_wait=self.on_wait)

    def allreduce_mean(self, tree: Any) -> Any:
        import jax

        summed = self.allreduce_sum(tree)
        return jax.tree.map(lambda x: x / self.world, summed)

    def allreduce_sum_async(self, tree: Any) -> Handle:
        """Start an allreduce on the background worker; returns a
        :class:`Handle` whose :meth:`~Handle.wait` yields exactly what
        :meth:`allreduce_sum` would have returned (or re-raises the
        worker-side error).  Submissions run in order; a subsequent sync
        collective drains them first, so op ids stay SPMD-agreed."""
        return self._submit("sum", tree)

    def allreduce_mean_async(self, tree: Any) -> Handle:
        return self._submit("mean", tree)

    def _submit(self, kind: str, tree: Any) -> Handle:
        if self._closed:
            raise PeerLost("collectives closed (round over)")
        obs.counter("coll/allreduce_async", unit="calls").inc()
        self._ensure_async_worker()
        handle = Handle()
        self._pending.append(handle)
        assert self._async_q is not None
        self._async_q.put((kind, tree, handle))
        return handle

    def _ensure_async_worker(self) -> None:
        if self._async_thread is None:
            # clone on the caller's thread so connection failures surface
            # here, not silently inside the worker
            self._async_client = self.client.clone()
            self._async_q = queue.Queue()
            self._async_thread = threading.Thread(
                target=self._async_loop, daemon=True,
                name="tpudist-coll-async")
            self._async_thread.start()

    def _async_loop(self) -> None:
        assert self._async_client is not None and self._async_q is not None
        try:
            while True:
                item = self._async_q.get()
                if item is None:
                    return
                kind, tree, handle = item
                try:
                    out = self._run_allreduce(
                        tree, self._async_client, on_wait=self.on_wait,
                        async_path=True)
                    if kind == "mean":
                        import jax

                        out = jax.tree.map(lambda x: x / self.world, out)
                    handle._finish(result=out)
                except BaseException as e:  # noqa: BLE001 - re-raised at wait()
                    handle._finish(exc=e)
        finally:
            self._async_client.close()

    def _drain_async(self) -> None:
        """Wait for every outstanding async collective to complete (their
        errors stay with their handles).  Keeps sync and async ops
        totally ordered, so operation ids agree across ranks."""
        pending, self._pending = self._pending, []
        for h in pending:
            h._event.wait()

    # -- the allreduce engine ----------------------------------------------

    def _run_allreduce(self, tree: Any, client: CoordClient,
                       on_wait: Callable[[], None] | None,
                       async_path: bool = False) -> Any:
        import jax

        obs.counter("coll/allreduce", unit="calls").inc()
        t_start = time.perf_counter()
        leaves, treedef = jax.tree.flatten(tree)
        np_leaves = [np.asarray(x) for x in leaves]
        total_bytes = sum(l.nbytes for l in np_leaves)
        if self.world == 1 or not np_leaves or total_bytes == 0:
            return jax.tree.unflatten(
                treedef, [np.array(l, copy=True) for l in np_leaves])
        buckets, plan = _fuse(np_leaves, self.config)
        if self.config.compress == "topk":
            self._inject_residuals(buckets)
        wire_bytes = sum(b.wire_nbytes for b in buckets)
        algo = self.config.algorithm
        if algo == "auto":
            algo = ("flat" if self.world <= 2
                    or total_bytes <= self.config.flat_max_bytes else "ring")
        if algo == "hier":
            # viable only when hosts >= 2 contiguous groups of equal size
            # > 1 exist; the check is a pure function of (world, config),
            # so every rank falls back to the same plain ring — an
            # elastic shrink to a non-divisible world must not wedge
            L = self.world // max(self.config.hosts, 1)
            if (self.config.hosts < 2 or L < 2
                    or self.world % self.config.hosts):
                obs.counter("coll/hier_fallback", unit="calls").inc()
                algo = "ring"
        tag = f"~algo={algo}~compress={self.config.compress}"
        obs.counter("coll/allreduce", unit="calls").inc()
        obs.counter(f"coll/allreduce{tag}", unit="calls").inc()
        if wire_bytes:
            ratio = total_bytes / wire_bytes
            obs.gauge("coll/compress_ratio").set(ratio)
            obs.gauge(f"coll/compress_ratio{tag}").set(ratio)
        p0, f0 = self.bytes_posted, self.bytes_fetched
        xp0, xf0 = self.bytes_posted_cross, self.bytes_fetched_cross
        op = self._begin_op(client)
        deadline = time.monotonic() + self.timeout_s
        io: _Prefetcher | None = None
        if algo in ("ring", "hier"):
            io = self._prefetcher(async_path)
        reducer = {"ring": self._ring, "hier": self._hier,
                   "flat": self._flat}[algo]
        reduced_buckets = reducer(buckets, op, client, io, deadline, on_wait)
        reduced: dict[str, list[np.ndarray]] = {}
        for b, vec in zip(buckets, reduced_buckets):
            reduced.setdefault(b.group, []).append(vec)
        out = _defuse(reduced, plan, np_leaves)
        obs.counter(f"coll/bytes_posted{tag}", unit="bytes").inc(
            self.bytes_posted - p0)
        obs.counter(f"coll/bytes_fetched{tag}", unit="bytes").inc(
            self.bytes_fetched - f0)
        if algo == "hier":
            obs.counter(f"coll/cross_bytes_posted{tag}", unit="bytes").inc(
                self.bytes_posted_cross - xp0)
            obs.counter(f"coll/cross_bytes_fetched{tag}", unit="bytes").inc(
                self.bytes_fetched_cross - xf0)
        dt = time.perf_counter() - t_start
        obs.histogram("coll/allreduce_s", unit="s").record(dt)
        obs.histogram(f"coll/allreduce_s{tag}", unit="s").record(dt)
        return jax.tree.unflatten(treedef, out)

    def _inject_residuals(self, buckets: list[_Bucket]) -> None:
        """Error feedback: fold the previous op's dropped mass into this
        rank's contribution, then arm fresh residual buffers for the
        drops the coming encodes will record.  Replacing the dict
        wholesale retires any bucket key the current tree no longer
        produces (a changed tree structure must not replay stale
        residuals into unrelated offsets)."""
        fresh: dict[tuple[int, int], np.ndarray] = {}
        for i, b in enumerate(buckets):
            if b.frac is None:
                continue
            prev = self._residuals.get((i, b.data.size))
            if prev is not None:
                # new array on purpose: b.data may alias the caller's
                # leaf (single-leaf groups fuse copy-free)
                b.data = b.data + prev
            b.residual = np.zeros(b.data.size, dtype=b.accum)
            fresh[(i, b.data.size)] = b.residual
        self._residuals = fresh

    def _prefetcher(self, async_path: bool) -> _Prefetcher:
        if async_path:
            if self._async_io is None:
                assert self._async_client is not None
                self._async_io = _Prefetcher(self._async_client, self._abort)
            return self._async_io
        if self._io is None:
            self._io = _Prefetcher(self.client, self._abort)
        return self._io

    def _flat(self, buckets: list[_Bucket], op: int, client: CoordClient,
              io: _Prefetcher | None, deadline: float,
              on_wait: Callable[[], None] | None) -> list[np.ndarray]:
        """All-gather + rank-ordered local reduce: one posted blob, one
        fetch per peer (staggered start — see :meth:`_peer_order`).  Best
        for tiny trees where ring round-trips dominate; O(world × size)
        fetch bytes otherwise."""
        payload = b"".join(_bucket_encode(b, b.data) for b in buckets)
        self._post(client, op, self._key(op, self.rank), payload)
        raws: dict[int, bytes] = {self.rank: payload}
        for r in self._peer_order():
            raws[r] = self._fetch(client, self._key(op, r), deadline, on_wait)
        out: list[np.ndarray] = []
        off = 0
        for b in buckets:
            blen = b.wire_nbytes
            acc: np.ndarray | None = None
            # reduction in RANK ORDER on every participant, over the
            # POSTED (wire-encoded) payloads — own contribution included,
            # so compression rounding is identical on every rank and
            # float non-associativity cannot diverge replicas
            for r in range(self.world):
                contrib = _bucket_decode(b, raws[r][off:off + blen],
                                         len(b.data))
                if acc is None:
                    acc = np.array(contrib, copy=True)
                else:
                    acc += contrib
            out.append(acc if acc is not None
                       else np.empty(0, b.accum))
            off += blen
        return out

    def _ring(self, buckets: list[_Bucket], op: int, client: CoordClient,
              io: _Prefetcher | None, deadline: float,
              on_wait: Callable[[], None] | None) -> list[np.ndarray]:
        """Chunked ring reduce-scatter + star all-gather over ALL ranks
        (see module docstring)."""
        jobs = [(str(bi), b, b.data, 0) for bi, b in enumerate(buckets)]
        return self._ring_pass(op, client, io, deadline, on_wait, jobs,
                               members=list(range(self.world)),
                               pos=self.rank)

    def _ring_pass(self, op: int, client: CoordClient,
                   io: _Prefetcher | None, deadline: float,
                   on_wait: Callable[[], None] | None,
                   jobs: list[tuple[str, _Bucket, np.ndarray, int]],
                   members: list[int], pos: int,
                   phase_rs: str = "rs",
                   phase_ag: str = "ag") -> list[np.ndarray]:
        """Chunked ring reduce-scatter + star all-gather over the ranks
        in ``members`` (this rank at position ``pos``) — the engine
        behind both the flat-world ring and each of hier's per-shard
        cross-host rings.  ``jobs`` carries ``(key token, bucket, vector,
        bucket-global base offset)`` per reduction; the base offset is
        where topk error-feedback drops land in the bucket's residual.
        The prefetcher keeps the NEXT hop's store wait in flight while
        this hop's chunk is being reduced."""
        assert io is not None
        ring = len(members)
        left = members[(pos - 1) % ring]
        own_final = (pos + 1) % ring  # ring position this rank finishes
        bounds = [_chunk_bounds(len(vec), ring) for _, _, vec, _ in jobs]
        # post every job's step-0 chunk up front: peers' prefetchers
        # find their first hop immediately, and bucket k+1's ring can
        # absorb store latency while bucket k reduces
        for ji, (tok, b, vec, base) in enumerate(jobs):
            lo, hi = bounds[ji][pos]
            self._post(client, op, self._ring_key(op, phase_rs, tok, 0,
                                                  members[pos]),
                       _bucket_encode(b, vec[lo:hi], base + lo))
        out: list[np.ndarray] = []
        for ji, (tok, b, vec, base) in enumerate(jobs):
            io.submit(self._ring_key(op, phase_rs, tok, 0, left), deadline)
            final_enc: bytes | None = None
            acc: np.ndarray | None = None
            for s in range(ring - 1):
                if s + 1 < ring - 1:
                    # pipeline: next hop's fetch rides the prefetcher
                    # while this hop decodes + reduces
                    io.submit(self._ring_key(op, phase_rs, tok, s + 1, left),
                              deadline)
                t0 = time.perf_counter()
                raw = self._take(
                    io, self._ring_key(op, phase_rs, tok, s, left), deadline,
                    on_wait)
                c = (pos - 1 - s) % ring
                lo, hi = bounds[ji][c]
                # fp32 (accum-dtype) add of the decoded partial and this
                # rank's own chunk; exactly ONE rank performs each hop,
                # so the per-chunk reduction order is ring-fixed
                acc = _bucket_decode(b, raw, hi - lo) + vec[lo:hi]
                if s + 1 < ring - 1:
                    self._post(
                        client, op,
                        self._ring_key(op, phase_rs, tok, s + 1,
                                       members[pos]),
                        _bucket_encode(b, acc, base + lo))
                else:
                    final_enc = _bucket_encode(b, acc, base + lo)
                obs.histogram("coll/ring_chunk_s", unit="s").record(
                    time.perf_counter() - t0)
            # all-gather over the store's star topology: post the finished
            # chunk ONCE; every peer fetches the owner's single post (ring
            # forwarding would re-upload each chunk world-2 more times)
            assert final_enc is not None
            self._post(client, op,
                       self._ring_key(op, phase_ag, tok, own_final),
                       final_enc)
            order = [(own_final + i) % ring for i in range(1, ring)]
            for c in order:
                io.submit(self._ring_key(op, phase_ag, tok, c), deadline)
            flo, fhi = bounds[ji][own_final]
            pieces: dict[int, np.ndarray] = {
                # decode own ENCODED bytes, not the raw accumulator: with
                # compression on, peers decode the posted wire payload —
                # bitwise agreement requires this rank to do the same
                own_final: _bucket_decode(b, final_enc, fhi - flo)}
            for c in order:
                raw = self._take(io, self._ring_key(op, phase_ag, tok, c),
                                 deadline, on_wait)
                lo, hi = bounds[ji][c]
                pieces[c] = _bucket_decode(b, raw, hi - lo)
            vec_out = np.empty(len(vec), b.accum)
            for c in range(ring):
                lo, hi = bounds[ji][c]
                vec_out[lo:hi] = pieces[c]
            out.append(vec_out)
        return out

    def _hier(self, buckets: list[_Bucket], op: int, client: CoordClient,
              io: _Prefetcher | None, deadline: float,
              on_wait: Callable[[], None] | None) -> list[np.ndarray]:
        """Hierarchical allreduce: (1) reduce-scatter each bucket within
        the host group — over the injected ICI plane when present, else
        simulated over the store in lossless accum-dtype bytes; (2) for
        shard ``j``, run the chunked cross-host ring among the H ranks
        holding shard ``j`` — ONE representative per host, so the
        cross-host wire carries ``2·(H-1)/H × size`` per HOST instead of
        per rank, and compression (bf16/topk) applies exactly here;
        (3) all-gather the finished shards back within the host.

        Determinism: each final shard has exactly one computation path
        (local-rank-ordered intra reduce, then ring-fixed hops), its
        ring all-gather bytes are decoded identically by all H holders,
        and the intra all-gather re-posts those identical arrays as raw
        bytes — so all ``world`` ranks agree bitwise.  All three phases
        share one deadline: a rank dying between phases surfaces as
        :class:`PeerLost` within one ``timeout_s``."""
        from tpudist.runtime import faults

        H = self.config.hosts
        L = self.world // H
        host, j = divmod(self.rank, L)
        locals_ = [host * L + i for i in range(L)]
        plane = self.intra
        if plane is not None and (plane.local_world != L
                                  or plane.local_index != j):
            raise ValueError(
                f"intra plane spans {plane.local_world} ranks at index "
                f"{plane.local_index}; hier expects host groups of {L} "
                f"with this rank at local index {j}")
        # the compiled ICI path carries f32/int32 exactly; wider dtypes
        # (f64, int64) would narrow silently through XLA, so those buckets
        # ride the lossless store path even when a plane is present — a
        # pure function of the bucket dtype, so every replica agrees
        on_plane = [plane is not None
                    and b.accum in (np.float32, np.int32)
                    for b in buckets]
        sbounds = [plane.bounds(len(b.data)) if on_plane[bi]
                   else _chunk_bounds(len(b.data), L)
                   for bi, b in enumerate(buckets)]
        faults.on_coll_phase("hier_intra", self.rank)
        # -- phase 1: intra-host reduce-scatter (lossless) ------------------
        shards: list[np.ndarray] = []
        for bi, b in enumerate(buckets):
            if not on_plane[bi]:
                for i in range(L):
                    if i == j:
                        continue
                    lo, hi = sbounds[bi][i]
                    self._post(
                        client, op,
                        self._ring_key(op, "hrs", bi, locals_[i], self.rank),
                        np.ascontiguousarray(b.data[lo:hi]).tobytes())
        for bi, b in enumerate(buckets):
            if on_plane[bi]:
                shards.append(np.asarray(plane.reduce_scatter(b.data),
                                         dtype=b.accum))
                continue
            lo, hi = sbounds[bi][j]
            acc: np.ndarray | None = None
            # fixed LOCAL-RANK order — the hier leg of the topology-
            # derived reduction-order contract
            for i in range(L):
                if locals_[i] == self.rank:
                    contrib: np.ndarray = b.data[lo:hi]
                else:
                    raw = self._fetch(
                        client,
                        self._ring_key(op, "hrs", bi, self.rank,
                                       locals_[i]),
                        deadline, on_wait)
                    contrib = np.frombuffer(raw, dtype=b.accum)
                acc = (np.array(contrib, copy=True) if acc is None
                       else acc + contrib)
            shards.append(acc if acc is not None
                          else np.empty(0, b.accum))
        # -- phase 2: cross-host ring, one representative per host ----------
        faults.on_coll_phase("hier_cross", self.rank)
        ring_members = [g * L + j for g in range(H)]
        jobs = [(f"{bi}.{j}", b, svec, sbounds[bi][j][0])
                for bi, (b, svec) in enumerate(zip(buckets, shards))]
        self._in_cross = True
        try:
            reduced_shards = self._ring_pass(
                op, client, io, deadline, on_wait, jobs,
                members=ring_members, pos=host,
                phase_rs="xrs", phase_ag="xag")
        finally:
            self._in_cross = False
        # -- phase 3: intra-host all-gather of finished shards --------------
        faults.on_coll_phase("hier_ag", self.rank)
        for bi, rvec in enumerate(reduced_shards):
            if not on_plane[bi]:
                self._post(client, op,
                           self._ring_key(op, "hag", bi, self.rank),
                           np.ascontiguousarray(rvec).tobytes())
        out: list[np.ndarray] = []
        for bi, (b, rvec) in enumerate(zip(buckets, reduced_shards)):
            if on_plane[bi]:
                out.append(np.asarray(plane.all_gather(rvec, len(b.data)),
                                      dtype=b.accum))
                continue
            vec = np.empty(len(b.data), b.accum)
            lo, hi = sbounds[bi][j]
            vec[lo:hi] = rvec
            for i in range(L):
                if i == j:
                    continue
                raw = self._fetch(
                    client, self._ring_key(op, "hag", bi, locals_[i]),
                    deadline, on_wait)
                lo, hi = sbounds[bi][i]
                vec[lo:hi] = np.frombuffer(raw, dtype=b.accum)
            out.append(vec)
        return out

    # -- broadcast / barrier ------------------------------------------------

    def broadcast(self, tree: Any, root: int = 0) -> Any:
        """Every rank returns root's pytree (``hvd.broadcast_parameters``
        role, `mnist_horovod.py:56` — state agreement after a resize).
        Payload rides uncompressed npz: state agreement must be EXACT,
        unlike gradient sync there is no accumulation to absorb rounding.

        Synchronizing: a trailing barrier guarantees every peer consumed
        the payload before anyone proceeds — without it, the root's op-2
        key GC could delete a broadcast a slow peer hasn't read yet
        (allreduce doesn't need this: posting op N implies having read
        every peer's op N-1)."""
        import jax

        self._drain_async()
        obs.counter("coll/broadcast", unit="calls").inc()
        leaves, treedef = jax.tree.flatten(tree)
        op = self._begin_op(self.client)
        if self.rank == root:
            self._post(self.client, op, self._key(op, root),
                       _dumps([np.asarray(x) for x in leaves]))
            out_tree = tree
        else:
            deadline = time.monotonic() + self.timeout_s
            out_tree = jax.tree.unflatten(
                treedef, _loads(self._fetch(
                    self.client, self._key(op, root), deadline,
                    self.on_wait)))
        self.barrier()
        return out_tree

    def barrier(self, timeout_s: float | None = None) -> None:
        """All-ranks barrier for this round (native store barrier)."""
        self._drain_async()
        obs.counter("coll/barrier", unit="calls").inc()
        op = self._begin_op(self.client)
        ok = self.client.barrier(
            f"{self.ns}/{self.round_id}/bar/{op}", self.world,
            timeout_s or self.timeout_s)
        if not ok:
            raise PeerLost(f"barrier {op} timed out at world {self.world}")

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Stop background workers (async executor + prefetchers) and
        abort any in-flight waits with :class:`PeerLost`.  Idempotent;
        does NOT close the caller-owned ``client``."""
        if self._closed:
            return
        self._closed = True
        self._abort.set()
        if self._async_q is not None:
            self._async_q.put(None)
        for io in (self._io, self._async_io):
            if io is not None:
                io.close()

    def close_round(self) -> None:
        """Delete every key this round left in the store (called before
        re-rendezvous so dead rounds cannot accumulate; idempotent —
        every survivor may call it).  Also tears down this instance's
        background workers: a dead round's async op must not keep
        fetching."""
        self.close()
        for key in self.client.keys(f"{self.ns}/{self.round_id}/"):
            try:
                self.client.delete(key)
            except ConnectionError:
                return
