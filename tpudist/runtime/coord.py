"""Rendezvous / coordination over the native (C++) store.

The control plane the reference delegates to external native libraries —
c10d TCPStore + torchrun rendezvous (`pytorch_elastic/mnist_ddp_elastic.py:5-6`)
and Horovod's C++ elastic controller with host discovery / blacklisting
(`horovod/horovod_mnist_elastic.py:108`) — re-built TPU-native: a small C++
TCP service (``native/coord.cpp``, loaded via :mod:`tpudist._native`)
offering a key-value store, blocking waits, named barriers, atomic counters,
and TTL heartbeats.  On top of it:

* :class:`CoordServer` / :class:`CoordClient` — thin ctypes handles.
* :class:`Rendezvous` — round-based worker assembly: every participant gets
  a dense rank and the round releases only when ``world_size`` workers have
  arrived (the c10d rendezvous contract).
* :class:`ElasticMonitor` — heartbeat publisher + liveness probe; its
  :meth:`check` raises :class:`~tpudist.elastic.loop.WorldChanged` when
  membership shifts, which :func:`~tpudist.elastic.loop.elastic_run` turns
  into rollback + re-rendezvous (Horovod-elastic semantics).

Only control-plane metadata moves through this service; tensors ride ICI via
XLA collectives (SURVEY.md §2.2).
"""

from __future__ import annotations

import ctypes
import os
import random
import threading
import time
import zlib

from tpudist import _native
from tpudist.runtime import faults as _faults

_VALUE_CAP = 1 << 20

# obs handle cached lazily: coord is imported by lightweight workers and
# must not pull the metrics registry (and its dependencies) at import
_retries_counter = None
_backoff_hist = None


def _obs_retries():
    global _retries_counter
    if _retries_counter is None:
        from tpudist import obs

        _retries_counter = obs.counter("coord/retries", unit="retries")
    return _retries_counter


def _obs_backoff():
    global _backoff_hist
    if _backoff_hist is None:
        from tpudist import obs

        _backoff_hist = obs.histogram(
            "coord/retry_backoff_s", unit="s",
            help="per-retry sleep the coord client's capped decorrelated-"
                 "jitter backoff chose")
    return _backoff_hist


class _OutageTracker:
    """Process-wide coord-availability bookkeeping.

    Every client op reports success/failure here; the first failure of a
    contiguous bad stretch opens an outage (``coord/unavailable`` gauge
    -> 1) and the first success after it closes it, recording the
    stretch's length into the ``coord/outage_s`` histogram.  The happy
    path is one lock-free attribute check."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._down_since: float | None = None
        self._gauge = None
        self._hist = None

    def _obs(self):
        if self._gauge is None:
            from tpudist import obs

            self._gauge = obs.gauge(
                "coord/unavailable", unit="bool",
                help="1 while the coord store is unreachable from this "
                     "process (brownout), 0 once an op succeeds again")
            self._hist = obs.histogram(
                "coord/outage_s", unit="s",
                help="length of each contiguous coord-unreachability "
                     "stretch, observed at reconnect")
        return self._gauge, self._hist

    def down(self) -> None:
        gauge, _ = self._obs()
        with self._lock:
            if self._down_since is None:
                self._down_since = time.monotonic()
        gauge.set(1)

    def up(self) -> None:
        if self._down_since is None:
            return
        with self._lock:
            since, self._down_since = self._down_since, None
        if since is None:
            return
        gauge, hist = self._obs()
        gauge.set(0)
        hist.record(time.monotonic() - since)

    def is_down(self) -> bool:
        return self._down_since is not None


outage = _OutageTracker()


class NativeUnavailable(RuntimeError):
    """The native coordination library could not be built/loaded."""


def _lib():
    lib = _native.load()
    if lib is None:
        raise NativeUnavailable(
            "libtpudist_native.so unavailable (no g++ or build failed)"
        )
    return lib


class CoordServer:
    """In-process coordination server; run one per job (usually on the
    coordinator host, or standalone via ``python -m tpudist.runtime.coord``)."""

    def __init__(self, port: int = 0) -> None:
        self._lib = _lib()
        self._h = self._lib.tcs_server_start(port)
        if not self._h:
            raise OSError(f"could not bind coordination server on port {port}")
        self.port: int = self._lib.tcs_server_port(self._h)

    def stop(self) -> None:
        if self._h:
            self._lib.tcs_server_stop(self._h)
            self._h = None

    def __enter__(self) -> "CoordServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


class CoordClient:
    """Client connection to a :class:`CoordServer` (possibly on another host;
    numeric IPs and hostnames both resolve).

    Transient-fault policy — the split is by IDEMPOTENCY, not by verb
    importance:

    * ``get`` / ``keys`` / ``live`` / ``heartbeat`` retry transparently
      on ``ConnectionError`` with bounded jittered exponential backoff
      (``retries`` attempts beyond the first; ``TPUDIST_COORD_RETRIES``
      overrides the default 2).  Re-running any of them is harmless: a
      read re-reads, and a heartbeat lease refresh applied twice is one
      refresh.  Each retry ticks the ``coord/retries`` counter so a
      flaky control plane is visible before it becomes an outage.
    * ``add``, ``barrier`` — and the writes ``set`` / ``delete`` /
      ``wait`` — surface REAL RPC errors IMMEDIATELY.  They are not
      idempotent (or their failure is not observable as such): an
      ``add`` whose reply was lost may have been applied, so a blind
      client-side replay could double-count a rank or double-arrive at
      a barrier — exactly the split-brain the rendezvous layer exists
      to prevent.  Callers own the recovery semantics for those (e.g. a
      fresh rendezvous round).  The ONE exception is the
      "connection refused" class — a :class:`~tpudist.runtime.faults.
      FaultInjected` outage fires BEFORE the request leaves the
      process, so nothing can have half-applied server-side and every
      verb retries it safely.

    Backoff is capped decorrelated jitter (``sleep = min(cap,
    uniform(base, 3 * prev_sleep))``): growth de-synchronizes a fleet
    of clients hit by the same blip, the cap bounds reconnect latency
    after a long brownout.  Each sleep lands in the
    ``coord/retry_backoff_s`` histogram; contiguous unreachability
    stretches drive the process-wide ``coord/unavailable`` gauge and
    ``coord/outage_s`` histogram (see :data:`outage`).

    Fault injection (:mod:`tpudist.runtime.faults`) hooks every op, so
    both halves of this contract are exercised deterministically in
    tests."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_ms: int = 10_000, retries: int | None = None,
                 retry_base_s: float = 0.02,
                 retry_cap_s: float | None = None) -> None:
        self._lib = _lib()
        self.host, self.port, self._timeout_ms = host, port, timeout_ms
        self._retries = (int(os.environ.get("TPUDIST_COORD_RETRIES", "2"))
                         if retries is None else int(retries))
        self._retry_base_s = float(retry_base_s)
        self._retry_cap_s = (
            float(os.environ.get("TPUDIST_COORD_RETRY_CAP_S", "1.0"))
            if retry_cap_s is None else float(retry_cap_s))
        self._h = self._lib.tcs_connect(host.encode(), port, timeout_ms)
        if not self._h:
            raise ConnectionError(f"could not reach coordination server {host}:{port}")
        # One request may be in flight per connection; serialize RPCs so a
        # client shared across threads (e.g. ElasticMonitor.check probing
        # from a collective's async worker while the owner thread polls)
        # cannot interleave frames on the socket.  RLock: keys() -> _joined.
        self._rpc_lock = threading.RLock()

    def clone(self) -> "CoordClient":
        """A fresh connection to the same server (one request is in flight
        per connection, so background threads need their own)."""
        return CoordClient(self.host, self.port, self._timeout_ms,
                           retries=self._retries,
                           retry_base_s=self._retry_base_s,
                           retry_cap_s=self._retry_cap_s)

    def _backoff(self, prev_sleep: float) -> float:
        """One capped decorrelated-jitter nap; returns the slept length
        (the next call's ``prev_sleep``)."""
        _obs_retries().inc()
        nap = min(self._retry_cap_s,
                  random.uniform(self._retry_base_s,
                                 max(self._retry_base_s, 3.0 * prev_sleep)))
        _obs_backoff().record(nap)
        time.sleep(nap)
        return nap

    def _retry(self, op: str, fn):
        """Run ``fn`` with the idempotent-op retry schedule (see class
        docstring)."""
        sleep = self._retry_base_s
        for attempt in range(self._retries + 1):
            try:
                result = fn()
            except ConnectionError:
                outage.down()
                if attempt >= self._retries:
                    raise
                sleep = self._backoff(sleep)
            else:
                outage.up()
                return result

    def _refused_gate(self, op: str) -> None:
        """Fault hook for the non-idempotent verbs.  During a DECLARED
        outage window FaultInjected fires BEFORE the RPC leaves the
        process — the refused class — so retrying it here cannot
        double-apply anything.  Probabilistic ``COORD_ERROR_P`` faults
        model an op whose server-side fate is unknown and keep
        surfacing immediately, like the real RPC errors raised by the
        verb body after this gate."""
        sleep = self._retry_base_s
        for attempt in range(self._retries + 1):
            try:
                _faults.coord_op(op)
            except _faults.FaultInjected:
                outage.down()
                if attempt >= self._retries \
                        or not _faults.plan().in_outage():
                    raise
                sleep = self._backoff(sleep)
            else:
                return

    def _direct(self, op: str, fn):
        """Run a non-idempotent verb: refused-class faults retry at the
        gate, the RPC itself runs at most once and surfaces its error
        immediately (split-brain rule)."""
        self._refused_gate(op)
        try:
            result = fn()
        except ConnectionError:
            outage.down()
            raise
        outage.up()
        return result

    # -- kv ----------------------------------------------------------------
    def set(self, key: str, value: bytes | str) -> None:
        if isinstance(value, str):
            value = value.encode()

        def once() -> None:
            with self._rpc_lock:
                if self._lib.tcs_set(self._h, key.encode(), value,
                                     len(value)) != 0:
                    raise ConnectionError("set failed")

        self._direct("set", once)

    def get(self, key: str) -> bytes | None:
        return self._retry("get", lambda: self._get_once(key))

    def _get_once(self, key: str) -> bytes | None:
        _faults.coord_op("get")
        cap = _VALUE_CAP
        while True:
            buf = ctypes.create_string_buffer(cap)
            out_len = ctypes.c_uint32()
            with self._rpc_lock:
                rc = self._lib.tcs_get(self._h, key.encode(), buf, cap,
                                       ctypes.byref(out_len))
            if rc == 1:
                return None
            if rc == 2:  # buffer too small; out_len holds the needed size
                cap = out_len.value
                continue
            if rc != 0:
                raise ConnectionError("get failed")
            return buf.raw[: out_len.value]

    def add(self, key: str, delta: int) -> int:
        # the RPC is NOT retried: a lost reply may still have
        # incremented the counter server-side — see the class docstring

        def once() -> int:
            with self._rpc_lock:
                v = self._lib.tcs_add(self._h, key.encode(), delta)
            if v == -(2**63):
                raise ConnectionError("add failed")
            return int(v)

        return self._direct("add", once)

    def wait(self, key: str, timeout_s: float = 30.0) -> bool:
        def once() -> bool:
            with self._rpc_lock:
                rc = self._lib.tcs_wait(self._h, key.encode(),
                                        int(timeout_s * 1000))
            if rc < 0:
                raise ConnectionError("wait failed")
            return rc == 0

        return self._direct("wait", once)

    def delete(self, key: str) -> None:
        def once() -> None:
            with self._rpc_lock:
                if self._lib.tcs_del(self._h, key.encode()) != 0:
                    raise ConnectionError("del failed")

        self._direct("delete", once)

    def keys(self, prefix: str = "") -> list[str]:
        def once() -> list[str]:
            _faults.coord_op("keys")
            joined = self._joined(
                lambda buf, cap, out: self._lib.tcs_keys(
                    self._h, prefix.encode(), buf, cap, out)
            )
            return joined.split(",") if joined else []

        return self._retry("keys", once)

    def _joined(self, call) -> str:
        cap = _VALUE_CAP
        while True:
            buf = ctypes.create_string_buffer(cap)
            out_len = ctypes.c_uint32()
            with self._rpc_lock:
                rc = call(buf, cap, ctypes.byref(out_len))
            if rc == 2:
                cap = out_len.value
                continue
            if rc != 0:
                raise ConnectionError("query failed")
            return buf.raw[: out_len.value].decode()

    # -- synchronization ---------------------------------------------------
    def barrier(self, name: str, count: int, timeout_s: float = 60.0) -> bool:
        """Block until ``count`` participants arrive at ``name``.  Returns
        False on timeout (the arrival is withdrawn server-side).

        Holds the connection's RPC lock for the whole wait — do not share
        a client between a thread that barriers and one that polls.

        NOT retried on error: a barrier arrival whose reply was lost may
        still be counted server-side, and a client-side replay would
        arrive twice — see the class docstring."""

        def once() -> bool:
            with self._rpc_lock:
                rc = self._lib.tcs_barrier(self._h, name.encode(), count,
                                           int(timeout_s * 1000))
            if rc < 0:
                raise ConnectionError("barrier failed")
            return rc == 0

        return self._direct("barrier", once)

    # -- liveness ----------------------------------------------------------
    def heartbeat(self, worker: str, ttl_s: float) -> None:
        """Refresh ``worker``'s liveness lease; ``ttl_s <= 0`` leaves.
        Idempotent (a lease refreshed twice is one refresh), so transient
        errors retry."""
        if _faults.drop_heartbeat():
            # injected heartbeat loss: the refresh silently never leaves
            # this process — the TTL plane will declare us dead while we
            # keep running (the false-positive a router must survive)
            return

        def once() -> None:
            _faults.coord_op("heartbeat")
            with self._rpc_lock:
                if self._lib.tcs_heartbeat(self._h, worker.encode(),
                                           int(ttl_s * 1000)) != 0:
                    raise ConnectionError("heartbeat failed")

        self._retry("heartbeat", once)

    def live(self) -> set[str]:
        def once() -> set[str]:
            _faults.coord_op("live")
            joined = self._joined(
                lambda buf, cap, out: self._lib.tcs_live(
                    self._h, buf, cap, out)
            )
            return set(joined.split(",")) if joined else set()

        return self._retry("live", once)

    def close(self) -> None:
        if self._h:
            self._lib.tcs_close(self._h)
            self._h = None

    def __enter__(self) -> "CoordClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Rendezvous:
    """Round-based rendezvous: dense rank assignment + all-arrived barrier.

    Each elastic restart is a new ``round``; all surviving/new workers call
    :meth:`join` with the same round number and world size, get back a dense
    rank in ``[0, world)``, and return together (the torchrun
    restart-on-membership-change contract, SURVEY.md §5)."""

    def __init__(self, client: CoordClient, namespace: str = "rdzv") -> None:
        self.client = client
        self.ns = namespace

    def join(self, round: int, world_size: int, timeout_s: float = 60.0) -> int:
        rank = self.client.add(f"{self.ns}/{round}/rank", 1) - 1
        if rank >= world_size:
            raise RuntimeError(
                f"rendezvous round {round} overflow: rank {rank} >= world {world_size}"
            )
        if not self.client.barrier(f"{self.ns}/{round}/barrier", world_size,
                                   timeout_s):
            raise TimeoutError(
                f"rendezvous round {round}: {world_size} workers did not arrive"
            )
        return rank

    def join_live(
        self,
        round: int,
        worker_id: str,
        timeout_s: float = 60.0,
        settle_s: float = 0.3,
        min_world: int = 1,
        min_world_grace_s: float = 10.0,
        superseded_key: str | None = None,
    ) -> tuple[int, int, list[str]]:
        """Dynamic-membership rendezvous: the round's world is whatever set
        of LIVE workers registers before membership stabilizes — the c10d
        contract torchrun relies on to re-form a world after failures
        (`mnist_ddp_elastic.py:5-6`), with the world size *discovered*, not
        prescribed.

        Protocol: register under ``{ns}/{round}/member/{id}``; poll until
        the registered-and-live set has been stable for ``settle_s``; then
        confirm agreement with a barrier keyed by the membership fingerprint
        — every participant must have computed the SAME set, else the
        barrier times out and the poll resumes (a straggler registered
        during someone's settle window).  Requires the caller's heartbeat
        (``ElasticMonitor.start``) to already be running so it appears in
        ``live()``.

        ``min_world`` is a SOFT assembly target: settling is deferred until
        that many members registered or ``min_world_grace_s`` elapsed —
        without it, the first arrival of a gang whose peers are still
        importing would form a world of one and force an immediate resize
        cascade.  After the grace the round forms with whoever is there
        (liveness over the target: a pre-registration death must not hang
        the gang).

        ``superseded_key`` names a store key publishing the highest round
        already FORMED (the elastic loop's ``elastic/round``).  If its
        value ever exceeds ``round``, this round is dead — the gang moved
        on while we lagged — and joining raises :class:`TimeoutError`
        immediately instead of settling into a splinter world of the
        leftovers (the caller's timeout handler re-reads the published
        round and jumps forward).

        Returns ``(rank, world_size, members)``; ranks are the sorted
        member order — dense, deterministic, identical everywhere.
        """
        self.client.set(f"{self.ns}/{round}/member/{worker_id}", b"1")
        start = time.monotonic()
        deadline = start + timeout_s
        grace_end = start + min(min_world_grace_s, timeout_s / 2)
        # second, longer grace for the defer-on-live-non-members rule
        # below: enough for a training gang to reach its next commit
        # point, but BOUNDED so one live-but-hung worker (heartbeat
        # thread alive, main thread wedged) cannot stall rendezvous to
        # the full timeout forever
        live_grace_end = start + min(3 * min_world_grace_s,
                                     0.75 * timeout_s)
        prefix = f"{self.ns}/{round}/member/"
        stable_since: float | None = None
        prev: frozenset[str] = frozenset()
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"rendezvous round {round}: membership never stabilized "
                    f"(last saw {sorted(prev)})")
            if superseded_key is not None:
                raw = self.client.get(superseded_key)
                if raw is not None and int(raw) > round:
                    raise TimeoutError(
                        f"rendezvous round {round} superseded: round "
                        f"{int(raw)} already formed")
            live = self.client.live()
            registered = frozenset(
                k[len(prefix):] for k in self.client.keys(prefix))
            if worker_id not in registered:
                # re-assert: a concurrent newer round's rank 0 may have
                # swept this round's member keys while we were settling
                # (the rounds-form-concurrently race) — registration must
                # survive the sweep or we poll into the timeout
                self.client.set(f"{prefix}{worker_id}", b"1")
                registered = registered | {worker_id}
            members = registered & live
            now = time.monotonic()
            if len(members) < min_world and (
                    now < grace_end
                    or (live - members and now < live_grace_end)):
                # Defer sub-target formation while workers are ALIVE but
                # not yet registered here: their heartbeats force the
                # incumbents' next check() to raise WorldChanged, so they
                # WILL arrive (or we time out and jump forward).  Without
                # this, a laggard whose grace expires before the gang's
                # next commit point forms a splinter world of one.  A
                # worker that died pre-registration is not in live(), so
                # the liveness-over-target rule still holds — and the
                # defer itself expires at live_grace_end, so a hung-but-
                # heartbeating worker can only delay formation, not
                # starve it into the max_rounds crash.
                prev, stable_since = members, now
                time.sleep(0.05)
                continue
            if worker_id not in members or members != prev:
                prev, stable_since = members, now
                time.sleep(0.05)
                continue
            if now - (stable_since or now) < settle_s:
                time.sleep(0.05)
                continue
            ordered = sorted(members)
            fingerprint = ",".join(ordered)
            # agreement barrier: releases only if every member computed
            # this exact set; a mismatch (someone saw a different set)
            # times out server-side and withdraws the arrival
            # crc32, not hash(): str hashing is salted per-process and the
            # key must be identical on every participant
            if self.client.barrier(
                    f"{self.ns}/{round}/agree/"
                    f"{zlib.crc32(fingerprint.encode())}/{len(ordered)}",
                    len(ordered), timeout_s=max(2 * settle_s, 1.0)):
                rank = ordered.index(worker_id)
                if rank == 0:
                    self._sweep_stale_rounds(round)
                return rank, len(ordered), ordered
            # Disagreement: reset `prev` so the next poll re-arms the
            # settle clock and the barrier is retried — clearing only
            # stable_since would livelock when membership stays unchanged
            # (prev == members would skip every re-arm branch forever).
            prev = frozenset()

    def _sweep_stale_rounds(self, current_round: int) -> None:
        """Delete dead rounds' member registrations — without this a
        long-lived elastic job leaks O(world) store keys per resize round
        (ADVICE r2).  Run by the new round's rank 0 after agreement; old
        rounds' keys are only read during their own join, so survivors of
        round N never look at round < N again."""
        prefix = f"{self.ns}/"
        for k in self.client.keys(prefix):
            rest = k[len(prefix):]
            head, _, tail = rest.partition("/")
            if not tail.startswith("member/"):
                continue
            try:
                r = int(head)
            except ValueError:
                continue
            if r < current_round:
                self.client.delete(k)


class ElasticMonitor:
    """Heartbeat publisher + membership watcher for one worker.

    A daemon thread refreshes this worker's TTL lease over its OWN
    connection — the caller's connection may sit inside a long blocking
    barrier (rendezvous for a slow-restarting peer), which must not starve
    heartbeats past the TTL.  :meth:`check` (called at commit points, the
    moral twin of Horovod's per-batch membership poll) raises
    :class:`WorldChanged` when the live set no longer matches the expected
    world."""

    def __init__(
        self,
        client: CoordClient,
        worker_id: str,
        ttl_s: float = 3.0,
        interval_s: float = 1.0,
    ) -> None:
        self.client = client
        self.worker_id = worker_id
        self.ttl_s = ttl_s
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._beat_client: CoordClient | None = None
        self.expected_world: int | None = None

    def start(self, expected_world: int) -> None:
        self.expected_world = expected_world
        self._beat_client = self.client.clone()
        self._beat_client.heartbeat(self.worker_id, self.ttl_s)
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()

    def _beat(self) -> None:
        # a coord brownout must not permanently kill the lease thread:
        # keep trying with jittered exponential backoff (capped well
        # below nothing — the lease has already lapsed server-side, the
        # point is to re-establish it the moment the store returns) and
        # snap back to the normal cadence on the first success
        delay = self.interval_s
        while not self._stop.wait(delay):
            try:
                self._beat_client.heartbeat(self.worker_id, self.ttl_s)
                delay = self.interval_s
            except ConnectionError:
                delay = min(8.0, max(delay, self.interval_s) * 2.0) \
                    * (0.5 + random.random())

    def check(self) -> None:
        """Raise ``WorldChanged(new_size)`` if membership shifted."""
        from tpudist.elastic.loop import WorldChanged

        live = self.client.live()
        if self.expected_world is not None and len(live) != self.expected_world:
            raise WorldChanged(len(live))

    def resize(self, new_world: int) -> None:
        self.expected_world = new_world

    def stop(self, graceful: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if graceful:
            try:
                self.client.heartbeat(self.worker_id, 0)  # leave
            except ConnectionError:
                pass
        if self._beat_client is not None:
            self._beat_client.close()
            self._beat_client = None


def main() -> None:  # pragma: no cover - CLI utility
    """Run a standalone coordination server: ``python -m tpudist.runtime.coord``."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(description="tpudist coordination server")
    ap.add_argument("--port", type=int, default=29400)
    args = ap.parse_args()
    server = CoordServer(args.port)
    print(f"tpudist coordination server listening on :{server.port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    while not stop.wait(1.0):
        pass
    server.stop()


if __name__ == "__main__":  # pragma: no cover
    main()
