"""KV-page migration for disaggregated prefill/decode serving.

The fleet's two request phases have opposite compute shapes: prefill is
compute-bound and bursty (one big attention pass over the whole prompt),
decode is memory-bound and steady (one token per step against a growing
KV cache).  A unified replica runs both, so a long prompt's prefill
chunks steal step time from every decoding lane behind it.
Disaggregation splits the fleet — ``ServeLoop(role="prefill")`` replicas
run chunked prefill to completion and HAND the finished KV state to
``ServeLoop(role="decode")`` replicas, which adopt the pages and decode
without ever re-running the prompt.

This module is the transport between them.  One payload per handoff::

    {"key":        router request key (the handoff's identity),
     "rid":        caller-visible request id,
     "prompt":     [token ids],          # re-prefill fallback needs it
     "max_new_tokens": int,              # post-degrade-clamp budget
     "first":      int,                  # token sampled at prefill end
     "true_len":   int,                  # prompt length in tokens
     "block_size": int,                  # exporter's KV page size
     "chain":      [ints],               # prefix-hash chain over the
                                         #   prompt's FULL blocks — the
                                         #   adopter recomputes and
                                         #   compares before trusting
                                         #   the pages
     "published_at": float,              # wall clock at publish; the
                                         #   adopter's handoff_wait_s
     "layers":     [{"k": ndarray, "v": ndarray}, ...]}
                                         # per paged layer, cache-walk
                                         #   order, [used_blocks, bs, F]

Two OPTIONAL riders extend the same schema to mid-decode migration
(priority preemption, hot/cold rebalancing, fast drain — PR 19):

    {"generated":  [token ids],          # tokens already emitted by the
                                         #   exporter, EXCLUDING "first";
                                         #   the adopter seeds its output
                                         #   with them, the chain covers
                                         #   prompt+generated, and
                                         #   true_len = len(prompt) +
                                         #   len(generated)
     "version":    int}                  # exporter's weights_version;
                                         #   an adopter on different
                                         #   weights refuses the pages
                                         #   and re-prefills (a roll in
                                         #   flight must not mix KV
                                         #   across versions)

Two transports implement one interface:

* :class:`CoordKVTransport` — the baseline path: the payload crosses the
  coord KV store at ``{ns}/kv/{key}`` as a checksummed
  ``kind="kv_migration"`` frame (:mod:`tpudist.runtime.wire`), arrays
  base64-packed with dtype/shape.  Works across any process/host pair
  that shares the store; a corrupt or missing payload surfaces as
  ``fetch() -> None`` and the decode side re-prefills from the prompt.
* :class:`IciKVTransport` — the fast path: device arrays move through an
  in-process registry, optionally ``jax.device_put`` onto the decode
  replica's device (a real device-to-device copy on multi-device
  hosts) — zero serialization, zero host round-trips for the page
  bytes.  Cross-HOST device transport would ride a formed
  :class:`~tpudist.runtime.ici.IciDataPlane` world the same way
  gradients do; the registry keeps the interface identical so that
  extension swaps in behind ``fetch``/``publish`` untouched.

Loss anywhere is survivable by construction: the payload is an
OPTIMIZATION, never the source of truth.  The request (with its prompt)
rides the router's journal; a decode replica whose ``fetch`` misses —
dropped payload (``TPUDIST_FAULT_HANDOFF_DROP``), checksum mismatch,
exporter SIGKILLed pre-commit (``TPUDIST_FAULT_KILL_AT_HANDOFF``) —
falls back to an ordinary prefill of the same prompt, and greedy
decoding over fleet-identical weights makes the fallback output
byte-identical to the migrated path.  See docs/DESIGN.md
"Disaggregated serving" for the two-stage scheduler and the
exactly-once ordering around the handoff commit.
"""

from __future__ import annotations

import base64
import time

import numpy as np

from tpudist import obs
from tpudist.runtime import faults, wire
from tpudist.utils.logging import get_logger

log = get_logger(__name__)

__all__ = ["KVTransport", "CoordKVTransport", "IciKVTransport",
           "make_transport", "encode_payload", "decode_payload",
           "payload_nbytes"]


# -- payload codec ---------------------------------------------------------

def _pack_array(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _unpack_array(d: dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["b64"]),
        dtype=np.dtype(d["dtype"])).reshape(d["shape"])


def encode_payload(payload: dict) -> dict:
    """JSON-safe document from a handoff payload (arrays base64-packed
    with dtype/shape so the decode side rebuilds them bit-exact)."""
    doc = {k: v for k, v in payload.items() if k != "layers"}
    doc["prompt"] = [int(t) for t in payload["prompt"]]
    doc["chain"] = [int(h) for h in payload["chain"]]
    if "generated" in payload:
        doc["generated"] = [int(t) for t in payload["generated"]]
    doc["layers"] = [{"k": _pack_array(np.asarray(l["k"])),
                      "v": _pack_array(np.asarray(l["v"]))}
                     for l in payload["layers"]]
    return doc


def decode_payload(doc: dict) -> dict:
    """Inverse of :func:`encode_payload`; raises ``KeyError`` /
    ``ValueError`` on a structurally broken document (callers treat
    that like a lost payload and re-prefill)."""
    out = {k: v for k, v in doc.items() if k != "layers"}
    out["prompt"] = [int(t) for t in doc["prompt"]]
    out["chain"] = [int(h) for h in doc["chain"]]
    if "generated" in doc:
        out["generated"] = [int(t) for t in doc["generated"]]
    out["layers"] = [{"k": _unpack_array(l["k"]),
                      "v": _unpack_array(l["v"])}
                     for l in doc["layers"]]
    return out


def payload_nbytes(payload: dict) -> int:
    """KV bytes a payload carries (the page arrays; the metadata is
    noise next to them)."""
    return int(sum(np.asarray(l["k"]).nbytes + np.asarray(l["v"]).nbytes
                   for l in payload.get("layers", ())))


# -- the transport interface -----------------------------------------------

class KVTransport:
    """One KV handoff channel: prefill side publishes, decode side
    fetches, the ROUTER deletes (payload lifecycle belongs to the
    request's owner, so an exporter death cannot leak it).

    Both implementations tick ``serve/handoffs`` and
    ``serve/handoff_bytes`` at publish and record ``serve/handoff_wait_s``
    (publish -> adoption wall time) at fetch, so the observability rows
    are transport-independent.
    """

    def __init__(self) -> None:
        self._obs_handoffs = obs.counter("serve/handoffs", unit="reqs")
        self._obs_bytes = obs.counter("serve/handoff_bytes", unit="bytes")
        self._obs_wait = obs.histogram("serve/handoff_wait_s", unit="s")

    def publish(self, key: str, payload: dict, *,
                kind: str = "handoff") -> tuple[str, int]:
        """Ship one payload; returns ``(ref, nbytes)``.  ``ref`` is the
        opaque token the decode side fetches by (it rides the router's
        dispatch doc and journal record).  ``kind`` selects which fault
        knob can swallow the publish: ``"handoff"`` (prefill→decode
        seam, ``HANDOFF_DROP``) or ``"migrate"`` (mid-decode
        preemption/rebalance/drain, ``MIGRATE_DROP``)."""
        raise NotImplementedError

    def fetch(self, ref: str) -> dict | None:
        """The payload behind ``ref``, or ``None`` when it is missing
        or fails verification — the caller's signal to re-prefill."""
        raise NotImplementedError

    def delete(self, ref: str) -> None:
        """Drop the payload (terminal consumption or redispatch).
        Idempotent; never raises on a missing ref."""
        raise NotImplementedError

    # shared metric tails -------------------------------------------------

    def _published(self, n: int) -> None:
        self._obs_handoffs.inc()
        self._obs_bytes.inc(n)

    def _fetched(self, payload: dict) -> dict:
        at = payload.get("published_at")
        if at is not None:
            self._obs_wait.record(max(0.0, time.time() - float(at)))
        return payload


class CoordKVTransport(KVTransport):
    """Baseline path: checksummed ``kv_migration`` frames in the coord
    KV store at ``{ns}/kv/{key}``.  Crosses any boundary the store does;
    costs one serialize + one round-trip each way."""

    def __init__(self, client, *, namespace: str = "fleet") -> None:
        super().__init__()
        self.client = client
        self.ns = namespace

    def publish(self, key: str, payload: dict, *,
                kind: str = "handoff") -> tuple[str, int]:
        ref = f"{self.ns}/kv/{key}"
        raw = wire.encode_record("kv_migration", encode_payload(payload))
        dropped = (faults.drop_migrate() if kind == "migrate"
                   else faults.drop_handoff())
        if dropped:
            # injected in-flight loss: the exporter believes the publish
            # landed (ref returned, done committed) but the payload
            # never reaches the store — the adopting side MUST fall back
            log.warning("disagg: %s_DROP injected; payload %s "
                        "lost in flight", kind.upper(), key)
        else:
            self.client.set(ref, raw)
        self._published(len(raw))
        return ref, len(raw)

    def fetch(self, ref: str) -> dict | None:
        try:
            raw = self.client.get(ref)
        except ConnectionError:
            return None
        if raw is None:
            return None
        try:
            doc = wire.decode_record(raw, expect="kv_migration",
                                     namespace=self.ns, key=ref)
            return self._fetched(decode_payload(doc))
        except (wire.WireError, KeyError, ValueError, TypeError) as e:
            # corrupt migration payload: never adopt it — count, drop,
            # and let the re-prefill fallback produce the exact output
            obs.counter("integrity/checksum_mismatch",
                        unit="payloads").inc()
            log.warning("disagg: undecodable KV payload %s (%s); "
                        "forcing re-prefill", ref, e)
            self.delete(ref)
            return None

    def delete(self, ref: str) -> None:
        try:
            self.client.delete(ref)
        except ConnectionError:
            pass


class IciKVTransport(KVTransport):
    """Fast path: payloads move by reference through an in-process
    registry, page arrays optionally ``device_put`` onto the decode
    side's device — the intra-host shape of device-to-device migration.
    Share ONE instance between the prefill and decode loops (the bench's
    colocated-fleet mode); a cross-host fleet uses the coord path or a
    formed ICI world behind this same interface."""

    def __init__(self, *, device=None) -> None:
        super().__init__()
        self.device = device
        self._store: dict[str, dict] = {}

    def publish(self, key: str, payload: dict, *,
                kind: str = "handoff") -> tuple[str, int]:
        ref = f"ici://{key}"
        n = payload_nbytes(payload)
        dropped = (faults.drop_migrate() if kind == "migrate"
                   else faults.drop_handoff())
        if dropped:
            log.warning("disagg: %s_DROP injected; payload %s "
                        "lost in flight", kind.upper(), key)
        else:
            if self.device is not None:
                import jax

                payload = dict(payload)
                payload["layers"] = [
                    {"k": jax.device_put(l["k"], self.device),
                     "v": jax.device_put(l["v"], self.device)}
                    for l in payload["layers"]]
            self._store[ref] = payload
        self._published(n)
        return ref, n

    def fetch(self, ref: str) -> dict | None:
        payload = self._store.get(ref)
        return None if payload is None else self._fetched(payload)

    def delete(self, ref: str) -> None:
        self._store.pop(ref, None)


def make_transport(kind: str, *, client=None, namespace: str = "fleet",
                   device=None) -> KVTransport:
    """``"coord"`` (baseline, needs ``client``) or ``"ici"`` (in-process
    fast path)."""
    if kind == "coord":
        if client is None:
            raise ValueError("coord transport needs a CoordClient")
        return CoordKVTransport(client, namespace=namespace)
    if kind == "ici":
        return IciKVTransport(device=device)
    raise ValueError(f"unknown KV transport {kind!r} "
                     f"(known: 'coord', 'ici')")
