"""Multi-host bootstrap and world introspection.

Replaces the reference's process-group / MPI / RPC initialization
(`mnist_ddp_elastic.py:22-27`, `mnist_horovod.py:28`,
`model_parallel_ResNet50.py:233-249` — SURVEY.md §2.2): on TPU, multi-host
training is one Python process per host, coordinated by
``jax.distributed.initialize`` over DCN; all tensor traffic then rides ICI via
XLA collectives, so there is no NCCL/gloo/MPI anywhere.

Single-host (including the CPU-simulated test meshes) needs no bootstrap at
all — ``initialize`` is a no-op there, mirroring how the reference's
``mp.spawn`` examples self-host a world on localhost
(`model_parallel_ResNet50.py:257-260`).
"""

from __future__ import annotations

import dataclasses
import os

import jax


@dataclasses.dataclass(frozen=True)
class DistributedContext:
    """What the reference reads from RANK/WORLD_SIZE env vars
    (`mnist_ddp_elastic.py:44-45`), derived here from the JAX runtime."""

    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_index == 0


_initialized = False

# Env vars whose presence indicates a managed multi-host launch where
# ``jax.distributed.initialize()`` can auto-detect everything.
_CLUSTER_ENV_HINTS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def _launcher_env() -> tuple[str, int, int] | None:
    """World description exported by :mod:`tpudist.runtime.launch` (the
    RANK/WORLD_SIZE env contract, `mnist_ddp_elastic.py:44-45`)."""
    addr = os.environ.get("TPUDIST_COORDINATOR")
    if not addr:
        return None
    return (addr, int(os.environ["TPUDIST_NUM_PROCESSES"]),
            int(os.environ["TPUDIST_PROCESS_ID"]))


def _detected_multihost() -> bool:
    """True only for an actual multi-host topology: a coordinator address,
    or a TPU worker list naming more than one host (a single-entry
    ``TPU_WORKER_HOSTNAMES`` — e.g. a tunneled single-chip dev box — needs
    no bootstrap and ``initialize`` would fail on it)."""
    if any(os.environ.get(k) for k in _CLUSTER_ENV_HINTS):
        return True
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hostnames.split(",") if h.strip()]) > 1


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> DistributedContext:
    """Bootstrap the (possibly multi-host) runtime. Idempotent.

    ``jax.distributed.initialize`` is invoked when (a) explicit arguments are
    given, or (b) a cluster environment is detectable (coordinator env vars /
    TPU pod metadata hints).  On a plain single host neither holds and no
    bootstrap is needed.  If a detected bootstrap *fails*, this raises rather
    than silently training N independent single-host models — the equivalent
    failure mode of forgetting ``init_process_group``
    (`mnist_ddp_elastic.py:26`).
    """
    global _initialized
    # Elastic recovery = process restart + re-jit (SURVEY.md §5), so a
    # restarted worker's compiles should be warm: honor an ambient
    # persistent-cache directory (the launcher/test env exports it; the
    # flag is harmless to set repeatedly).
    if os.environ.get("TPUDIST_CACHE_DIR"):
        from tpudist.runtime.cache import enable_compilation_cache

        enable_compilation_cache()
    launcher = _launcher_env()
    if launcher is not None and coordinator_address is None:
        coordinator_address, num_processes, process_id = launcher
    explicit = coordinator_address is not None or num_processes is not None
    detected = _detected_multihost()
    if not _initialized and (explicit or detected):
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    return world_info()


def world_info() -> DistributedContext:
    return DistributedContext(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_rank() -> int:
    """Index of this process among processes on the same host (LOCAL_RANK
    equivalent, `mnist_ddp_elastic.py:45`). TPU runs one process per host, so
    this is 0 except under explicit multi-process-per-host launches."""
    return int(os.environ.get("TPUDIST_LOCAL_RANK", "0"))
