"""Deterministic fault injection for the coordination and serve planes.

Robustness code that is only exercised by real hardware failures is
robustness code that has never run.  This module is the harness that
makes the failure paths *testable*: a process-wide :class:`FaultPlan`,
parsed once from ``TPUDIST_FAULT_*`` environment variables, whose hooks
are threaded through the hot points where production faults actually
land —

* :meth:`FaultPlan.coord_op` — called by every
  :class:`~tpudist.runtime.coord.CoordClient` RPC; injects a
  :class:`FaultInjected` (a ``ConnectionError`` subclass, so the
  production retry/error paths handle it exactly like a dropped TCP
  connection) with probability ``coord_error_p`` and/or a ``coord_delay_s``
  stall with probability ``coord_delay_p``;
* :meth:`FaultPlan.drop_heartbeat` — consulted by
  ``CoordClient.heartbeat``; once process uptime passes
  ``heartbeat_stop_after_s`` every lease refresh is silently swallowed,
  so the worker *looks* dead to the TTL plane while actually running
  (the false-positive case a router must survive);
* :meth:`FaultPlan.on_segment` — called by the serve loop after each
  dispatched decode segment; after ``kill_after_segments`` dispatches the
  process SIGKILLs *itself* — an uncatchable death mid-decode, the
  harshest replica-loss shape;
* :meth:`FaultPlan.drop_publish` — consulted by
  :meth:`~tpudist.obs.aggregate.MetricsPublisher.publish`; once uptime
  passes ``publish_drop_after_s`` every metrics publish is silently
  swallowed while heartbeats keep flowing — the replica stays LIVE to
  the TTL plane but its gauges age out, which is exactly the health
  monitor's ``stale`` verdict (a wedged metrics thread, a partitioned
  obs plane) as opposed to ``lost``.

Determinism: the probabilistic knobs draw from one ``random.Random``
seeded by ``TPUDIST_FAULT_SEED`` (default 0), so a failing CI run
replays bit-identically.  With no ``TPUDIST_FAULT_*`` variable set the
plan is inert and every hook is a near-free early return — production
code pays one attribute check.

Environment knobs (all optional):

==================================  =========================================
``TPUDIST_FAULT_COORD_ERROR_P``     probability a coord RPC raises
                                    :class:`FaultInjected` before running
``TPUDIST_FAULT_COORD_DELAY_P``     probability a coord RPC sleeps first
``TPUDIST_FAULT_COORD_DELAY_S``     the injected sleep (default 0.05 s)
``TPUDIST_FAULT_HEARTBEAT_STOP_AFTER_S``
                                    drop all heartbeats once process uptime
                                    exceeds this many seconds
``TPUDIST_FAULT_KILL_AFTER_SEGMENTS``
                                    SIGKILL self after this many dispatched
                                    serve segments
``TPUDIST_FAULT_PUBLISH_DROP``      drop all metrics publishes once process
                                    uptime exceeds this many seconds
                                    (heartbeats keep flowing: the replica
                                    goes ``stale``, not ``lost``)
``TPUDIST_FAULT_HEARTBEAT_DELAY_S``
                                    swallow heartbeats while process uptime
                                    is BELOW this many seconds — a slow
                                    joiner (snapshot restore + compile)
                                    that registers long before its first
                                    lease refresh lands
``TPUDIST_FAULT_KILL_AT_WARMUP``    SIGKILL self at the replica warmup
                                    point (after registration, before the
                                    first heartbeat) — a joiner torn down
                                    mid-warmup
``TPUDIST_FAULT_CANARY_CORRUPT``    flip a token in every completion whose
                                    request id starts with ``canary`` — a
                                    green pool that warms, heartbeats, and
                                    then serves WRONG output
``TPUDIST_FAULT_AUTOSCALE_POLL_DELAY_S``
                                    stall every autoscaler control poll by
                                    this many seconds — a wedged control
                                    plane that must not lose requests
``TPUDIST_FAULT_ROUTER_KILL_AFTER_POLLS``
                                    SIGKILL self after this many router
                                    ``_poll`` iterations — a control-plane
                                    crash mid-spike whose recovery path
                                    (``--recover``) must finish every
                                    in-flight request exactly once
``TPUDIST_FAULT_COORD_OUTAGE_AT_S``
                                    start of a full-store unreachability
                                    window (process uptime, seconds): EVERY
                                    coord RPC raises :class:`FaultInjected`
                                    while the window is open — a coord
                                    brownout, as distinct from the per-op
                                    ``COORD_ERROR_P`` coin flips.  Because
                                    the fault fires BEFORE the RPC leaves
                                    the process, no op can have half-
                                    applied — the "connection refused"
                                    class, safely retriable for all verbs
``TPUDIST_FAULT_COORD_OUTAGE_S``    the outage window's length (default
                                    5 s once ``COORD_OUTAGE_AT_S`` is set)
``TPUDIST_FAULT_FLIP_WIRE_BITS``    ``N`` or ``N:M`` — flip one bit in every
                                    Nth coord payload this process commits
                                    (``N:M`` stops after M flips total):
                                    silent wire corruption the checksummed
                                    frame must catch and the router must
                                    quarantine
``TPUDIST_FAULT_NAN_AFTER_TOKENS``  poison the decode segment's logits to
                                    NaN once the serve loop has emitted this
                                    many tokens — in-band compute corruption
                                    the lane guard must freeze into a
                                    ``corrupt_segment`` verdict
``TPUDIST_FAULT_PROBE_FAIL``        flip a token in the first N completions
                                    whose request id starts with ``probe`` —
                                    a quarantined replica that keeps failing
                                    its golden probes (N large: retirement;
                                    N small: fail-then-reinstate)
``TPUDIST_FAULT_HANDOFF_DROP``      swallow the first N KV-migration payload
                                    publishes: the prefill replica believes
                                    the handoff landed but the payload never
                                    reaches the store — the decode side must
                                    fall back to re-prefill with identical
                                    output
``TPUDIST_FAULT_KILL_AT_HANDOFF``   SIGKILL self immediately after
                                    publishing the Nth KV-migration payload,
                                    BEFORE committing the handoff done
                                    record — the router must redispatch the
                                    request exactly-once (re-prefill on a
                                    surviving replica, byte-identical
                                    output)
``TPUDIST_FAULT_MIGRATE_DROP``      swallow the first N preemption/rebalance
                                    MIGRATE payload publishes (the
                                    mid-decode analogue of
                                    ``HANDOFF_DROP``): the exporting
                                    replica believes the migration landed
                                    but the pages never reach the store —
                                    the adopting side must fall back to a
                                    byte-identical re-prefill
``TPUDIST_FAULT_KILL_AT_MIGRATE``   SIGKILL self immediately after
                                    publishing the Nth MIGRATE payload,
                                    BEFORE committing the migrate done
                                    record — the router's death sweep must
                                    redispatch the in-flight request
                                    exactly-once with byte-identical output
``TPUDIST_FAULT_COLL_KILL_PHASE``   SIGKILL self when the hierarchical
                                    allreduce reaches this phase boundary
                                    (``hier_intra`` / ``hier_cross`` /
                                    ``hier_ag``) — a rank dying between the
                                    intra-host and cross-host phases, which
                                    survivors must surface as ``PeerLost``
                                    within ONE shared ``timeout_s``
``TPUDIST_FAULT_COLL_KILL_RANK``    restrict ``COLL_KILL_PHASE`` to this
                                    collective rank (default: every rank —
                                    only useful with the in-process raise
                                    mode, see ``coll_kill_raise``)
``TPUDIST_FAULT_SEED``              RNG seed for the probabilistic knobs
==================================  =========================================
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time

__all__ = ["FaultInjected", "RouterKilled", "FaultPlan", "plan",
           "install", "reset", "coord_op", "drop_heartbeat",
           "drop_publish", "on_segment", "on_warmup", "corrupt_canary",
           "autoscale_poll", "on_router_poll", "flip_wire_bits",
           "poison_logits", "corrupt_probe", "drop_handoff",
           "on_handoff_published", "drop_migrate",
           "on_migrate_published", "on_coll_phase"]

ENV_PREFIX = "TPUDIST_FAULT_"


class FaultInjected(ConnectionError):
    """An injected coordination-plane failure.  Subclasses
    ``ConnectionError`` so production error handling (CoordClient's
    idempotent-op retry, callers' except clauses) treats it exactly like
    a real dropped connection."""


class RouterKilled(RuntimeError):
    """Raised by :meth:`FaultPlan.on_router_poll` instead of SIGKILL
    when ``router_kill_raise`` is set: the in-process router-crash shape
    the offline simulator uses — FleetSim catches it, builds a fresh
    Router on the same fabric, and runs the REAL ``recover()`` path on
    the virtual clock.  Live chaos keeps the real SIGKILL."""


def _env_float(environ, name: str) -> float | None:
    raw = environ.get(ENV_PREFIX + name)
    if raw is None or raw.strip() == "":
        return None
    return float(raw)


class FaultPlan:
    """One process's fault schedule.  Thread-safe: the serve loop, the
    heartbeat daemon, and collective workers all consult the same plan."""

    def __init__(
        self,
        coord_error_p: float = 0.0,
        coord_delay_p: float = 0.0,
        coord_delay_s: float = 0.05,
        heartbeat_stop_after_s: float | None = None,
        kill_after_segments: int | None = None,
        publish_drop_after_s: float | None = None,
        heartbeat_delay_s: float | None = None,
        kill_at_warmup: bool = False,
        canary_corrupt: bool = False,
        autoscale_poll_delay_s: float | None = None,
        router_kill_after_polls: int | None = None,
        router_kill_raise: bool = False,
        coord_outage_at_s: float | None = None,
        coord_outage_s: float = 5.0,
        flip_wire_bits: str | int | None = None,
        nan_after_tokens: int | None = None,
        probe_fail: int | None = None,
        handoff_drop: int | None = None,
        kill_at_handoff: int | None = None,
        migrate_drop: int | None = None,
        kill_at_migrate: int | None = None,
        coll_kill_phase: str | None = None,
        coll_kill_rank: int | None = None,
        coll_kill_raise: bool = False,
        seed: int = 0,
    ) -> None:
        if not 0.0 <= coord_error_p <= 1.0:
            raise ValueError(
                f"coord_error_p must be in [0, 1], got {coord_error_p}")
        if not 0.0 <= coord_delay_p <= 1.0:
            raise ValueError(
                f"coord_delay_p must be in [0, 1], got {coord_delay_p}")
        self.coord_error_p = float(coord_error_p)
        self.coord_delay_p = float(coord_delay_p)
        self.coord_delay_s = float(coord_delay_s)
        self.heartbeat_stop_after_s = heartbeat_stop_after_s
        self.kill_after_segments = (None if kill_after_segments is None
                                    else int(kill_after_segments))
        self.publish_drop_after_s = publish_drop_after_s
        self.heartbeat_delay_s = heartbeat_delay_s
        self.kill_at_warmup = bool(kill_at_warmup)
        self.canary_corrupt = bool(canary_corrupt)
        self.autoscale_poll_delay_s = autoscale_poll_delay_s
        if router_kill_after_polls is not None \
                and int(router_kill_after_polls) < 1:
            raise ValueError(
                f"router_kill_after_polls must be >= 1, got "
                f"{router_kill_after_polls}")
        self.router_kill_after_polls = (
            None if router_kill_after_polls is None
            else int(router_kill_after_polls))
        self.router_kill_raise = bool(router_kill_raise)
        if coord_outage_at_s is not None and coord_outage_s <= 0:
            raise ValueError(
                f"coord_outage_s must be > 0, got {coord_outage_s}")
        self.coord_outage_at_s = coord_outage_at_s
        self.coord_outage_s = float(coord_outage_s)
        # wire corruption spec "N" (every Nth payload, forever) or
        # "N:M" (every Nth, but stop after M flips — the transient
        # corruption shape whose reinstatement path the bench drives)
        self.flip_wire_every: int | None = None
        self.flip_wire_max: int | None = None
        if flip_wire_bits is not None:
            spec = str(flip_wire_bits)
            every, _, cap = spec.partition(":")
            try:
                self.flip_wire_every = int(every)
                self.flip_wire_max = int(cap) if cap else None
            except ValueError:
                raise ValueError(
                    f"flip_wire_bits must be 'N' or 'N:M', got {spec!r}"
                ) from None
            if self.flip_wire_every < 1 or (
                    self.flip_wire_max is not None
                    and self.flip_wire_max < 1):
                raise ValueError(
                    f"flip_wire_bits counts must be >= 1, got {spec!r}")
        if nan_after_tokens is not None and int(nan_after_tokens) < 0:
            raise ValueError(
                f"nan_after_tokens must be >= 0, got {nan_after_tokens}")
        self.nan_after_tokens = (None if nan_after_tokens is None
                                 else int(nan_after_tokens))
        if probe_fail is not None and int(probe_fail) < 1:
            raise ValueError(
                f"probe_fail must be >= 1, got {probe_fail}")
        self.probe_fail = None if probe_fail is None else int(probe_fail)
        if handoff_drop is not None and int(handoff_drop) < 1:
            raise ValueError(
                f"handoff_drop must be >= 1, got {handoff_drop}")
        self.handoff_drop = (None if handoff_drop is None
                             else int(handoff_drop))
        if kill_at_handoff is not None and int(kill_at_handoff) < 1:
            raise ValueError(
                f"kill_at_handoff must be >= 1, got {kill_at_handoff}")
        self.kill_at_handoff = (None if kill_at_handoff is None
                                else int(kill_at_handoff))
        if migrate_drop is not None and int(migrate_drop) < 1:
            raise ValueError(
                f"migrate_drop must be >= 1, got {migrate_drop}")
        self.migrate_drop = (None if migrate_drop is None
                             else int(migrate_drop))
        if kill_at_migrate is not None and int(kill_at_migrate) < 1:
            raise ValueError(
                f"kill_at_migrate must be >= 1, got {kill_at_migrate}")
        self.kill_at_migrate = (None if kill_at_migrate is None
                                else int(kill_at_migrate))
        _COLL_PHASES = ("hier_intra", "hier_cross", "hier_ag")
        if coll_kill_phase is not None and coll_kill_phase not in \
                _COLL_PHASES:
            raise ValueError(
                f"coll_kill_phase must be one of {_COLL_PHASES}, got "
                f"{coll_kill_phase!r}")
        self.coll_kill_phase = coll_kill_phase
        self.coll_kill_rank = (None if coll_kill_rank is None
                               else int(coll_kill_rank))
        self.coll_kill_raise = bool(coll_kill_raise)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._segments = 0
        self._router_polls = 0
        self._wire_payloads = 0
        self._handoffs_published = 0
        self._migrates_published = 0
        self._born = time.monotonic()
        # per-kind injection tallies, inspectable by tests
        self.injected = {"coord_error": 0, "coord_delay": 0,
                         "heartbeat_drop": 0, "publish_drop": 0,
                         "heartbeat_delay": 0, "canary_corrupt": 0,
                         "autoscale_delay": 0, "coord_outage": 0,
                         "router_kill": 0, "wire_flip": 0,
                         "nan_logits": 0, "probe_corrupt": 0,
                         "handoff_drop": 0, "handoff_kill": 0,
                         "migrate_drop": 0, "migrate_kill": 0,
                         "coll_kill": 0}
        self.active = bool(coord_error_p or coord_delay_p
                           or heartbeat_stop_after_s is not None
                           or kill_after_segments is not None
                           or publish_drop_after_s is not None
                           or heartbeat_delay_s is not None
                           or kill_at_warmup or canary_corrupt
                           or autoscale_poll_delay_s is not None
                           or router_kill_after_polls is not None
                           or coord_outage_at_s is not None
                           or self.flip_wire_every is not None
                           or self.nan_after_tokens is not None
                           or self.probe_fail is not None
                           or self.handoff_drop is not None
                           or self.kill_at_handoff is not None
                           or self.migrate_drop is not None
                           or self.kill_at_migrate is not None
                           or self.coll_kill_phase is not None)

    @classmethod
    def from_env(cls, environ=None) -> "FaultPlan":
        env = os.environ if environ is None else environ
        kill = _env_float(env, "KILL_AFTER_SEGMENTS")
        hb = _env_float(env, "HEARTBEAT_STOP_AFTER_S")
        rkill = _env_float(env, "ROUTER_KILL_AFTER_POLLS")
        outage_s = _env_float(env, "COORD_OUTAGE_S")
        return cls(
            coord_error_p=_env_float(env, "COORD_ERROR_P") or 0.0,
            coord_delay_p=_env_float(env, "COORD_DELAY_P") or 0.0,
            coord_delay_s=(_env_float(env, "COORD_DELAY_S")
                           if _env_float(env, "COORD_DELAY_S") is not None
                           else 0.05),
            heartbeat_stop_after_s=hb,
            kill_after_segments=None if kill is None else int(kill),
            publish_drop_after_s=_env_float(env, "PUBLISH_DROP"),
            heartbeat_delay_s=_env_float(env, "HEARTBEAT_DELAY_S"),
            kill_at_warmup=bool(_env_float(env, "KILL_AT_WARMUP") or 0),
            canary_corrupt=bool(_env_float(env, "CANARY_CORRUPT") or 0),
            autoscale_poll_delay_s=_env_float(env, "AUTOSCALE_POLL_DELAY_S"),
            router_kill_after_polls=None if rkill is None else int(rkill),
            coord_outage_at_s=_env_float(env, "COORD_OUTAGE_AT_S"),
            coord_outage_s=5.0 if outage_s is None else outage_s,
            flip_wire_bits=(env.get(ENV_PREFIX + "FLIP_WIRE_BITS") or None),
            nan_after_tokens=(
                None if _env_float(env, "NAN_AFTER_TOKENS") is None
                else int(_env_float(env, "NAN_AFTER_TOKENS"))),
            probe_fail=(None if _env_float(env, "PROBE_FAIL") is None
                        else int(_env_float(env, "PROBE_FAIL"))),
            handoff_drop=(
                None if _env_float(env, "HANDOFF_DROP") is None
                else int(_env_float(env, "HANDOFF_DROP"))),
            kill_at_handoff=(
                None if _env_float(env, "KILL_AT_HANDOFF") is None
                else int(_env_float(env, "KILL_AT_HANDOFF"))),
            migrate_drop=(
                None if _env_float(env, "MIGRATE_DROP") is None
                else int(_env_float(env, "MIGRATE_DROP"))),
            kill_at_migrate=(
                None if _env_float(env, "KILL_AT_MIGRATE") is None
                else int(_env_float(env, "KILL_AT_MIGRATE"))),
            coll_kill_phase=(env.get(ENV_PREFIX + "COLL_KILL_PHASE")
                             or None),
            coll_kill_rank=(
                None if _env_float(env, "COLL_KILL_RANK") is None
                else int(_env_float(env, "COLL_KILL_RANK"))),
            seed=int(_env_float(env, "SEED") or 0),
        )

    # -- hooks -------------------------------------------------------------

    def in_outage(self) -> bool:
        """True while the declared full-store unreachability window is
        open (uptime in ``[coord_outage_at_s, coord_outage_at_s +
        coord_outage_s)``)."""
        if self.coord_outage_at_s is None:
            return False
        uptime = time.monotonic() - self._born
        return (self.coord_outage_at_s
                <= uptime
                < self.coord_outage_at_s + self.coord_outage_s)

    def coord_op(self, op: str) -> None:
        """Maybe delay, maybe raise — called before every coord RPC.
        During a declared outage window EVERY op raises: the fault fires
        before the RPC leaves the process, so nothing can have half-
        applied server-side — the retriable "connection refused" class,
        unlike a real mid-RPC failure."""
        if self.in_outage():
            with self._lock:
                self.injected["coord_outage"] += 1
            raise FaultInjected(f"injected fault: coord outage ({op})")
        if not (self.coord_error_p or self.coord_delay_p):
            return
        with self._lock:
            delay = (self.coord_delay_p
                     and self._rng.random() < self.coord_delay_p)
            error = (self.coord_error_p
                     and self._rng.random() < self.coord_error_p)
            if delay:
                self.injected["coord_delay"] += 1
            if error:
                self.injected["coord_error"] += 1
        if delay:
            time.sleep(self.coord_delay_s)
        if error:
            raise FaultInjected(f"injected fault: coord {op}")

    def drop_heartbeat(self) -> bool:
        """True when this process's heartbeats should be swallowed —
        either forever once uptime passes ``heartbeat_stop_after_s`` (a
        dying worker), or only WHILE uptime is below ``heartbeat_delay_s``
        (a slow-warming joiner whose first lease refresh lags its
        registration)."""
        uptime = time.monotonic() - self._born
        if self.heartbeat_delay_s is not None \
                and uptime < self.heartbeat_delay_s:
            with self._lock:
                self.injected["heartbeat_delay"] += 1
            return True
        if self.heartbeat_stop_after_s is None:
            return False
        if uptime < self.heartbeat_stop_after_s:
            return False
        with self._lock:
            self.injected["heartbeat_drop"] += 1
        return True

    def drop_publish(self) -> bool:
        """True when this process's metrics publishes should be
        swallowed (the heartbeat keeps flowing — staleness, not
        death)."""
        if self.publish_drop_after_s is None:
            return False
        if time.monotonic() - self._born < self.publish_drop_after_s:
            return False
        with self._lock:
            self.injected["publish_drop"] += 1
        return True

    def on_segment(self) -> None:
        """Count one dispatched serve segment; SIGKILL self at the
        configured count.  SIGKILL (not sys.exit) on purpose: no atexit,
        no finally blocks, no graceful heartbeat leave — the process
        simply vanishes mid-decode, as a torn pod does."""
        if self.kill_after_segments is None:
            return
        with self._lock:
            self._segments += 1
            n = self._segments
        if n >= self.kill_after_segments:
            os.kill(os.getpid(), signal.SIGKILL)

    def on_warmup(self) -> None:
        """SIGKILL self at the replica warmup point — a joiner that
        registered but dies before its first heartbeat.  The router's
        grace window must not leave its registration pinned forever."""
        if self.kill_at_warmup:
            os.kill(os.getpid(), signal.SIGKILL)

    def corrupt_canary(self, rid: str) -> bool:
        """True when this completion's tokens should be corrupted: the
        green-pool failure the blue-green canary check exists to catch
        (warmed, heartbeating, and WRONG)."""
        if not (self.canary_corrupt and rid.startswith("canary")):
            return False
        with self._lock:
            self.injected["canary_corrupt"] += 1
        return True

    def flip_wire_bits(self, payload: bytes) -> bytes:
        """Maybe corrupt one coord payload about to be committed: every
        ``flip_wire_every``-th payload gets ONE bit flipped (capped at
        ``flip_wire_max`` flips when set).  The flip lands past any
        frame header, so the CHECKSUM — not a parse error — is what has
        to catch it, exactly like a real in-flight bit flip."""
        if self.flip_wire_every is None or not payload:
            return payload
        with self._lock:
            self._wire_payloads += 1
            fire = (self._wire_payloads % self.flip_wire_every == 0
                    and (self.flip_wire_max is None
                         or self.injected["wire_flip"]
                         < self.flip_wire_max))
            if fire:
                self.injected["wire_flip"] += 1
        if not fire:
            return payload
        pos = min(len(payload) - 1, max(9, len(payload) // 2))
        return (payload[:pos] + bytes([payload[pos] ^ 0x10])
                + payload[pos + 1:])

    def poison_logits(self, tokens_served: int) -> bool:
        """True when this decode segment's logits should be poisoned to
        NaN: the serve loop has emitted at least ``nan_after_tokens``
        tokens — overflowed-accumulator corruption appearing mid-run,
        which the in-graph lane guard must freeze rather than emit."""
        if (self.nan_after_tokens is None
                or tokens_served < self.nan_after_tokens):
            return False
        with self._lock:
            self.injected["nan_logits"] += 1
        return True

    def corrupt_probe(self, rid: str) -> bool:
        """True when this golden-probe completion's tokens should be
        corrupted (first ``probe_fail`` probes only): a quarantined
        replica that is still wrong when re-probed."""
        if not (self.probe_fail and rid.startswith("probe")):
            return False
        with self._lock:
            if self.injected["probe_corrupt"] >= self.probe_fail:
                return False
            self.injected["probe_corrupt"] += 1
        return True

    def drop_handoff(self) -> bool:
        """True when this KV-migration payload should be lost in flight:
        the first ``handoff_drop`` publishes are swallowed — the prefill
        replica's publish "succeeds" but the payload never lands, so the
        decode side's fetch misses and must re-prefill from the prompt
        (byte-identical output is the contract being tested)."""
        if self.handoff_drop is None:
            return False
        with self._lock:
            if self.injected["handoff_drop"] >= self.handoff_drop:
                return False
            self.injected["handoff_drop"] += 1
        return True

    def on_handoff_published(self) -> None:
        """Count one published KV-migration payload; SIGKILL self at the
        configured count — after the payload is in the store but BEFORE
        the handoff done record commits.  The harshest handoff-window
        death: the router's sweep must redispatch the request (the
        orphaned payload is garbage-collected) and the retry must
        produce byte-identical output."""
        if self.kill_at_handoff is None:
            return
        with self._lock:
            self._handoffs_published += 1
            n = self._handoffs_published
            if n >= self.kill_at_handoff:
                self.injected["handoff_kill"] += 1
        if n >= self.kill_at_handoff:
            os.kill(os.getpid(), signal.SIGKILL)

    def drop_migrate(self) -> bool:
        """True when this preemption/rebalance MIGRATE payload should be
        lost in flight: the first ``migrate_drop`` publishes are
        swallowed — the exporting replica's publish "succeeds" but the
        pages never land, so the adopting side's fetch misses and must
        re-prefill the original prompt (byte-identical output is the
        contract being tested)."""
        if self.migrate_drop is None:
            return False
        with self._lock:
            if self.injected["migrate_drop"] >= self.migrate_drop:
                return False
            self.injected["migrate_drop"] += 1
        return True

    def on_migrate_published(self) -> None:
        """Count one published MIGRATE payload; SIGKILL self at the
        configured count — after the pages are in the store but BEFORE
        the migrate done record commits.  The harshest migration-window
        death: the router's death sweep must redispatch the request
        (the orphaned payload is garbage-collected) and the retry must
        produce byte-identical output."""
        if self.kill_at_migrate is None:
            return
        with self._lock:
            self._migrates_published += 1
            n = self._migrates_published
            if n >= self.kill_at_migrate:
                self.injected["migrate_kill"] += 1
        if n >= self.kill_at_migrate:
            os.kill(os.getpid(), signal.SIGKILL)

    def on_coll_phase(self, phase: str, rank: int | None = None) -> None:
        """Kill this participant when the hierarchical allreduce crosses
        the configured phase boundary (``hier_intra`` → before the
        intra-host reduce-scatter, ``hier_cross`` → after it and before
        the cross-host ring, ``hier_ag`` → before the intra all-gather).
        SIGKILL by default — the process vanishes with its intra-phase
        contribution already consumed, the harshest mid-collective
        death; with ``coll_kill_raise`` it raises :class:`FaultInjected`
        instead so an in-process (thread-per-rank) harness can play the
        dying rank while its survivor threads assert the ``PeerLost``
        deadline.  ``coll_kill_rank`` scopes the fault to one collective
        rank — required in thread harnesses, where every rank shares the
        process-wide plan."""
        if self.coll_kill_phase is None or phase != self.coll_kill_phase:
            return
        if self.coll_kill_rank is not None and rank != self.coll_kill_rank:
            return
        with self._lock:
            self.injected["coll_kill"] += 1
        if self.coll_kill_raise:
            raise FaultInjected(
                f"injected fault: collective rank {rank} killed at {phase}")
        os.kill(os.getpid(), signal.SIGKILL)

    def autoscale_poll(self) -> None:
        """Stall one autoscaler control poll (a wedged control plane —
        the data plane must keep serving, just without scaling)."""
        if self.autoscale_poll_delay_s is None:
            return
        with self._lock:
            self.injected["autoscale_delay"] += 1
        time.sleep(self.autoscale_poll_delay_s)

    def on_router_poll(self) -> None:
        """Count one router ``_poll`` iteration; crash the router at the
        configured count.  SIGKILL by default (live chaos: no finally
        blocks, the assignment table simply vanishes); with
        ``router_kill_raise`` it raises :class:`RouterKilled` instead so
        an in-process harness (the simulator) can catch the crash and
        drive the real recovery path."""
        if self.router_kill_after_polls is None:
            return
        with self._lock:
            self._router_polls += 1
            n = self._router_polls
        if n >= self.router_kill_after_polls:
            with self._lock:
                self.injected["router_kill"] += 1
            if self.router_kill_raise:
                # one-shot: recovery's own polls must not re-trip it
                self.router_kill_after_polls = None
                raise RouterKilled(
                    f"injected fault: router killed at poll {n}")
            os.kill(os.getpid(), signal.SIGKILL)


_INERT = FaultPlan()
_plan: FaultPlan | None = None


def plan() -> FaultPlan:
    """The process-wide plan, parsed from the environment on first use."""
    global _plan
    if _plan is None:
        _plan = FaultPlan.from_env()
    return _plan


def install(new_plan: FaultPlan | None) -> None:
    """Replace the process-wide plan (tests); ``None`` re-reads the
    environment on next use."""
    global _plan
    _plan = new_plan


def reset() -> None:
    install(None)


# module-level conveniences: the hot-path call sites use these so the
# inert case is one global load + one attribute check
def coord_op(op: str) -> None:
    p = plan()
    if p.active:
        p.coord_op(op)


def drop_heartbeat() -> bool:
    p = plan()
    return p.active and p.drop_heartbeat()


def drop_publish() -> bool:
    p = plan()
    return p.active and p.drop_publish()


def on_segment() -> None:
    p = plan()
    if p.active:
        p.on_segment()


def on_warmup() -> None:
    p = plan()
    if p.active:
        p.on_warmup()


def corrupt_canary(rid: str) -> bool:
    p = plan()
    return p.active and p.corrupt_canary(rid)


def flip_wire_bits(payload: bytes) -> bytes:
    p = plan()
    return p.flip_wire_bits(payload) if p.active else payload


def poison_logits(tokens_served: int) -> bool:
    p = plan()
    return p.active and p.poison_logits(tokens_served)


def corrupt_probe(rid: str) -> bool:
    p = plan()
    return p.active and p.corrupt_probe(rid)


def drop_handoff() -> bool:
    p = plan()
    return p.active and p.drop_handoff()


def on_handoff_published() -> None:
    p = plan()
    if p.active:
        p.on_handoff_published()


def drop_migrate() -> bool:
    p = plan()
    return p.active and p.drop_migrate()


def on_migrate_published() -> None:
    p = plan()
    if p.active:
        p.on_migrate_published()


def on_coll_phase(phase: str, rank: int | None = None) -> None:
    p = plan()
    if p.active:
        p.on_coll_phase(phase, rank)


def autoscale_poll() -> None:
    p = plan()
    if p.active:
        p.autoscale_poll()


def on_router_poll() -> None:
    p = plan()
    if p.active:
        p.on_router_poll()
