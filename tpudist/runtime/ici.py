"""Resizable XLA-collective data plane for elastic training.

Round 3 left one place where the framework wasn't TPU-first: after a
TTL-detected resize, :mod:`tpudist.elastic.worker` synced gradients through
:class:`~tpudist.runtime.collectives.HostCollectives` — the store that is
supposed to be control-plane-only (``native/coord.cpp``'s own contract, and
the role split the reference itself draws at
`server_model_data_parallel.py:119-122`: RPC control on :29501 vs gloo data
on :29500).  This module moves the post-resize data plane onto XLA
collectives: after every rendezvous round, the gang bootstraps a fresh
``jax.distributed`` world sized to the round, and gradient sync runs as a
compiled ``jax.lax.pmean`` over a ``Mesh`` spanning the member processes —
ICI/DCN on TPU pods, gloo TCP on the CPU backend used by the tests.

How an in-process RESIZE of a compiled-collective world works:

1. every device value that must survive is snapshotted to host numpy
   (:func:`host_snapshot` — ``clear_backends`` invalidates every
   ``jax.Array``, and typed PRNG keys additionally need their impl
   recorded to round-trip);
2. the previous distributed runtime is torn down: the coordination
   client disconnects, jax's distributed global state is reset, then
   ``jax.extend.backend.clear_backends()`` drops the backend and every
   jit cache (nothing may hold a stale executable across the swap);
3. the round's rank 0 spawns a fresh coordination service in its OWN
   detached process (:mod:`tpudist.runtime.ici_service` — a worker-
   hosted leader is fatal to elasticity: a coordination client whose
   leader becomes unreachable ``LOG(FATAL)``s its process) and publishes
   the address under ``{ns}/{round}/addr`` in the coord store (control
   plane); everyone connects at the new size and the new backend's
   devices form the data mesh.

Failure detection is symmetric by construction: collectives are
dispatched asynchronously and POLLED (:meth:`IciCollectives._wait_ready`)
with the TTL membership probe in between, so a member death surfaces as
``WorldChanged``/a collective error on every survivor within one TTL —
whatever its position in the gloo ring — measured end-to-end in the
kill -9 tests (`tests/test_elastic_ici.py`).

On real TPU pods the device plane cannot be re-sized in-process (device
ownership is fixed at runtime startup); there the same rendezvous drives
the gang-restart path (``runtime/launch.py --max-restarts``) and this
module's ``initialize`` runs once per process lifetime with the TPU
defaults.  The in-process resize is exercised on the CPU backend, which is
also where the reference's elastic examples run their own data plane
(gloo, `mnist_ddp_elastic.py:26`).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Any, Callable

import numpy as np

from tpudist.runtime.coord import CoordClient
from tpudist.utils.logging import get_logger

log = get_logger(__name__)

# message fragments that identify a failed XLA/gloo collective or a dead
# distributed runtime — the ICI analog of HostCollectives' PeerLost
_COLLECTIVE_FAILURE_MARKS = (
    "gloo",                    # "Gloo all-reduce failed: ..."
    "connection reset",
    "connection refused",
    "coordination service",
    "deadline exceeded",
    "barrier timed out",
    "socket closed",
    "distributed runtime",
)


class FormationTimeout(RuntimeError):
    """The round's distributed world never formed (rank 0 vanished before
    publishing, or a peer died inside the connection barrier)."""


def is_collective_failure(exc: BaseException) -> bool:
    """Does this exception look like a peer-loss inside the compiled data
    plane (rather than a bug)?  Matched on the message because XLA surfaces
    gloo/coordination failures as plain ``ValueError``/``RuntimeError`` —
    and ONLY on those types, so an unrelated exception whose message
    happens to contain e.g. "socket closed" is not silently treated as a
    membership change.  ``ConnectionError`` is excluded explicitly even
    though it is not a RuntimeError/ValueError: the coord-store client
    raises it, and a control-plane outage must propagate, not trigger
    re-rendezvous against a dead store."""
    if isinstance(exc, ConnectionError):
        return False
    if not isinstance(exc, (RuntimeError, ValueError)):
        return False
    msg = str(exc).lower()
    return any(mark in msg for mark in _COLLECTIVE_FAILURE_MARKS)


def host_snapshot(tree: Any) -> tuple[Any, Callable[[], Any]]:
    """Snapshot ``tree`` to host numpy and return ``(host_tree, restore)``.

    ``restore()`` rebuilds the tree on whatever backend is current when it
    runs — the backend-swap helper: raw numpy survives
    ``clear_backends()``; typed PRNG keys are re-wrapped from their
    recorded impl (a plain spec object, backend-independent)."""
    import jax

    from tpudist.utils.trees import is_prng_key, tree_to_numpy

    impls = jax.tree.map(
        lambda leaf: jax.random.key_impl(leaf) if is_prng_key(leaf)
        else False, tree)
    host = tree_to_numpy(tree)

    def restore() -> Any:
        import jax.numpy as jnp

        return jax.tree.map(
            lambda h, impl: (jax.random.wrap_key_data(jnp.asarray(h),
                                                      impl=impl)
                             if impl is not False else h),
            host, impls)

    return host, restore


# retired distributed-runtime handles (see IciDataPlane.teardown): kept
# alive on purpose so their destructors never fire a disconnect RPC at a
# dead/retired leader
_GRAVEYARD: list = []

from tpudist.runtime.launch import _free_port  # noqa: E402 - one probe, shared


class IciDataPlane:
    """Per-round ``jax.distributed`` world manager for the elastic worker.

    One instance lives for the whole worker; :meth:`form` is called once
    per rendezvous round and returns the round's data mesh.  The coord
    store carries ONLY the address agreement (control plane); every
    gradient byte of the formed round rides XLA collectives.

    Args:
      client: coord-store connection (main-thread use only).
      namespace: store key prefix for the address agreement.
      host_ip: address peers can reach THIS process's coordinator on when
        it is rank 0.  Default loopback (single-host tests); multi-host
        launches set ``TPUDIST_HOST_IP``.
      heartbeat_timeout_s / init_timeout_s: forwarded to
        ``jax.distributed.initialize``; init failures (a peer died between
        rendezvous and formation) surface as catchable errors within
        ``init_timeout_s``.  The heartbeat timeout defaults to a day:
        liveness detection belongs to the TTL store (seconds, not the
        coordination service's 100 s), and a parked world's client must
        never reach its missed-heartbeat handler.
    """

    def __init__(
        self,
        client: CoordClient,
        namespace: str = "ici",
        host_ip: str | None = None,
        heartbeat_timeout_s: int = 86400,
        init_timeout_s: int = 30,
    ) -> None:
        self.client = client
        self.ns = namespace
        self.host_ip = (host_ip or os.environ.get("TPUDIST_HOST_IP")
                        or "127.0.0.1")
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.init_timeout_s = init_timeout_s
        self._active_round: int | None = None

    # -- lifecycle ---------------------------------------------------------

    def form(
        self,
        round_id: int,
        rank: int,
        world: int,
        on_wait: Callable[[], None] | None = None,
    ) -> Any:
        """Bootstrap the round's ``jax.distributed`` world; returns its
        1-axis data :class:`jax.sharding.Mesh` (axis ``"data"``, one entry
        per member process's devices).

        MUST be called with every to-survive value already host-resident
        (:func:`host_snapshot`): the previous backend — including the
        single-process one used during model init — is torn down here.

        Raises on formation failure (address-agreement timeout, a peer
        dying mid-init); callers treat that like any other membership
        change and re-rendezvous."""
        import jax
        from jax._src import distributed as jdist
        from jax._src.lib import _jax as _jaxlib

        self.teardown()
        addr = self._agree_address(round_id, rank, world, on_wait)
        log.info("ici round %d: initialize rank %d/%d at %s",
                 round_id, rank, world, addr)
        # The coordination client is built directly (not via
        # ``jax.distributed.initialize``): the service lives in its OWN
        # spawned process (see :mod:`tpudist.runtime.ici_service` for why
        # a worker-hosted leader is fatal to elasticity), and the client
        # must never fire a disconnect RPC from a destructor.
        client = _jaxlib.get_distributed_runtime_client(
            addr, rank,
            init_timeout=self.init_timeout_s,
            heartbeat_timeout=self.heartbeat_timeout_s,
            shutdown_on_destruction=False,
            use_compression=True,
            recoverable=True)
        client.connect()
        jdist.global_state.client = client
        jdist.global_state.process_id = rank
        jdist.global_state.num_processes = world
        jdist.global_state.coordinator_address = addr
        self._active_round = round_id
        devices = jax.devices()
        if len(devices) % world != 0:
            raise RuntimeError(
                f"ici round {round_id}: {len(devices)} devices not "
                f"divisible by world {world}")
        return jax.sharding.Mesh(np.asarray(devices), ("data",))

    def teardown(self) -> None:
        """Retire the current distributed world plus hard-reset jax's
        backend/jit caches.  Idempotent; safe at any point of the world's
        lifecycle, including with peers already dead.

        The disconnect is CLEAN even after member deaths because the
        service this client talks to lives in its own process
        (:mod:`tpudist.runtime.ici_service`), not in any worker — there
        is no "leader died" case.  Should the disconnect still fail
        (e.g. the service was swept by a much newer round), the client is
        parked in a module graveyard so its destructor never retries the
        RPC."""
        from jax._src import distributed as jdist

        client = jdist.global_state.client
        if client is not None:
            try:
                client.shutdown()
            except Exception as e:  # noqa: BLE001 - teardown must proceed
                log.warning("ici teardown: disconnect failed (%s); parking",
                            str(e)[:200])
                _GRAVEYARD.append(client)
        if jdist.global_state.preemption_sync_manager is not None:
            _GRAVEYARD.append(jdist.global_state.preemption_sync_manager)
        jdist.global_state.client = None
        jdist.global_state.service = None
        jdist.global_state.preemption_sync_manager = None
        from jax.extend.backend import clear_backends

        clear_backends()
        # The PJRT client (and with it the gloo TCP pairs) is freed only
        # when its LAST reference dies, and a blocked peer of a half-dead
        # world only unblocks when those sockets close — the unblock
        # latency IS the gang's re-rendezvous latency.  clear_backends
        # drops the backend registry and jit caches, but jax ALSO interns
        # every Mesh in a global dict keyed by its device tuple
        # (jax._src.mesh._mesh_object_dict), which pins the dead client's
        # Device objects forever; purge it (meshes re-intern on demand)
        # and collect now rather than whenever the GC next runs.
        from jax._src import mesh as jmesh

        jmesh._mesh_object_dict.clear()
        import gc

        gc.collect()
        self._active_round = None

    def finalize(self, rank: int, barrier: Callable[[], None]) -> None:
        """End-of-run cleanup: disconnect, synchronize so every member has
        disconnected, then let rank 0 reap every service process this
        plane ever spawned (same-host reach; remote leftovers self-expire
        via ``--max-lifetime-s``)."""
        self.teardown()
        barrier()
        if rank == 0:
            self._sweep(upto=None)

    # -- service spawning + address agreement (control plane) --------------

    def _agree_address(self, round_id: int, rank: int, world: int,
                       on_wait: Callable[[], None] | None) -> str:
        key = f"{self.ns}/{round_id}/addr"
        if rank == 0:
            port = self._spawn_service(round_id, world)
            addr = f"{self.host_ip}:{port}"
            self.client.set(key, addr)
            # Reap services ≥ 2 generations stale: every member of the
            # CURRENT round has (by registering) already finished tearing
            # down round-1's world, so nothing can still be disconnecting
            # from a round-2 service.  Sweeping round-1 here could race a
            # laggard's clean disconnect.
            self._sweep(upto=round_id - 2)
            return addr
        deadline = time.monotonic() + self.init_timeout_s
        while True:
            raw = self.client.get(key)
            if raw is not None:
                return raw.decode()
            if on_wait is not None:
                on_wait()
            if time.monotonic() > deadline:
                raise FormationTimeout(
                    f"rank 0 never published {key} within "
                    f"{self.init_timeout_s}s")
            self.client.wait(key, timeout_s=0.2)

    def _spawn_service(self, round_id: int, world: int) -> int:
        """Launch this round's coordination service in its own process and
        return its port; publishes ``{ns}/{round}/svc`` = ``pid:host`` for
        the generational sweep."""
        import select
        import subprocess
        import sys

        import tempfile

        port = _free_port()
        # stderr to its own log file: the daemon outlives this worker, so
        # inheriting a harness's stderr PIPE would hold its write end open
        # (the harness's read-to-EOF then blocks on the daemon's lifetime)
        errlog_path = os.path.join(
            tempfile.gettempdir(), f"tpudist_ici_service_{port}.log")
        errlog = open(errlog_path, "wb")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "tpudist.runtime.ici_service",
                 "--port", str(port), "--world", str(world),
                 "--heartbeat-timeout-s", str(self.heartbeat_timeout_s)],
                stdout=subprocess.PIPE, stderr=errlog,
                start_new_session=True)  # detach: must outlive this worker
        finally:
            errlog.close()
        ready, _, _ = select.select([proc.stdout], [], [],
                                    self.init_timeout_s)
        if not ready or proc.stdout.readline().strip() != b"ready":
            proc.kill()
            # FormationTimeout: the worker loop treats this like any other
            # membership change and re-rendezvouses (a port-bind race or a
            # slow host must not crash the gang member)
            raise FormationTimeout(
                f"ici round {round_id}: service process never came up "
                f"(its stderr is in {errlog_path})")
        proc.stdout.close()
        self.client.set(f"{self.ns}/{round_id}/svc",
                        f"{proc.pid}:{socket.gethostname()}")
        return port

    def _sweep(self, upto: int | None) -> None:
        """SIGTERM service processes of rounds ≤ ``upto`` (all when None)
        and drop their store keys.  Only same-host pids are reachable;
        others are left to their ``--max-lifetime-s`` backstop."""
        me = socket.gethostname()
        for key in self.client.keys(f"{self.ns}/"):
            parts = key.split("/")
            if len(parts) != 3 or parts[2] not in ("svc", "addr"):
                continue
            try:
                r = int(parts[1])
            except ValueError:
                continue
            if upto is not None and r > upto:
                continue
            if parts[2] == "svc":
                raw = self.client.get(key)
                if raw is not None:
                    pid_s, _, host = raw.decode().partition(":")
                    if host == me:
                        try:
                            os.kill(int(pid_s), 15)
                        except (OSError, ValueError):
                            pass
            try:
                self.client.delete(key)
            except ConnectionError:
                return


class IciCollectives:
    """Gradient-sync collectives over the compiled XLA path — the drop-in
    data-plane replacement for :class:`HostCollectives.allreduce_mean`
    (same pytree-in/pytree-out API, so a train loop swaps planes without
    changing shape).

    Each call builds (once per tree structure, AOT-cached) a jitted
    ``shard_map`` whose body is ``jax.lax.pmean`` over the mesh's data
    axis, stacks every member's contribution along that axis, and returns
    this process's (averaged) row.  ``last_hlo`` holds the compiled HLO of
    the most recent executable — the proof that gradients ride
    ``all-reduce``, asserted by the elastic ICI tests."""

    def __init__(self, mesh: Any,
                 on_check: Callable[[], None] | None = None,
                 timeout_s: float = 60.0) -> None:
        import jax

        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.world = int(np.prod(list(mesh.shape.values())))
        # processes contribute one tree each, replicated across their own
        # devices (the TPU topology: one process per host, several chips)
        me = jax.process_index()
        self.local_rows = sum(
            1 for d in mesh.devices.flat if d.process_index == me)
        self.num_processes = jax.process_count()
        # _stack_local contributes one row per LOCAL DEVICE and the pmean
        # averages over device rows; with heterogeneous per-process device
        # counts that would be a device-weighted mean, not the per-process
        # mean allreduce_mean promises (and allreduce_sum = mean × procs
        # would be silently wrong).  Fail loudly at formation instead —
        # counting EVERY process's rows (mesh.devices is global), so the
        # failure is symmetric: no member proceeds into a collective its
        # peers refused to join.
        from collections import Counter

        per_proc = Counter(d.process_index for d in mesh.devices.flat)
        if len(set(per_proc.values())) > 1:
            raise RuntimeError(
                f"IciCollectives requires a uniform device count per "
                f"process; mesh devices per process: {dict(per_proc)}")
        self._sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(self.axis))
        self._execs: dict[Any, Any] = {}
        self.on_check = on_check
        self.timeout_s = timeout_s
        self.last_hlo: str | None = None

    def release(self) -> None:
        """Drop every reference into the backend — compiled executables,
        mesh, sharding (each pins the client via its Device objects; a
        dead round's client must actually be freed so its sockets close —
        see :meth:`IciDataPlane.teardown`).  The object is unusable
        afterwards."""
        self._execs.clear()
        self.mesh = None
        self._sharding = None

    def _tree_pmean(self, tree: Any) -> Any:
        import jax

        return jax.tree.map(
            lambda x: jax.lax.pmean(x, self.axis), tree)

    def _stack_local(self, tree: Any) -> Any:
        """Each process contributes its local tree as one row PER LOCAL
        DEVICE of a global ``[world_devices, ...]`` array sharded along
        the data axis (uniform replication keeps the mean exact)."""
        import jax

        def put(leaf):
            leaf = np.asarray(leaf)
            local = np.repeat(leaf[None], self.local_rows, axis=0)
            return jax.make_array_from_process_local_data(
                self._sharding, local, (self.world, *leaf.shape))

        return jax.tree.map(put, tree)

    def _executable(self, global_tree: Any) -> Any:
        import jax

        key = jax.tree.structure(global_tree), tuple(
            (leaf.shape, str(leaf.dtype))
            for leaf in jax.tree.leaves(global_tree))
        exe = self._execs.get(key)
        if exe is None:
            from tpudist.obs.xla import compile_watch

            spec = jax.sharding.PartitionSpec(self.axis)
            fn = jax.jit(jax.shard_map(
                self._tree_pmean, mesh=self.mesh,
                in_specs=spec, out_specs=spec))
            with compile_watch("ici"):
                exe = fn.lower(global_tree).compile()
            self._execs[key] = exe
            # rendered once per compile (the text is identical for a
            # cache hit and re-rendering a large module every step isn't)
            self.last_hlo = exe.as_text()
            try:
                from tpudist import obs

                obs.recorder.note_hlo(self.last_hlo)
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass
        return exe

    def allreduce_mean(self, tree: Any) -> Any:
        """Mean-reduce a pytree across the mesh's member processes through
        one compiled all-reduce; returns host numpy (the elastic loop
        commits host-side)."""
        if self.on_check is not None:
            # membership probe BEFORE entering the collective: a peer the
            # TTL already declared dead would leave us stuck on an op
            # that can never complete
            self.on_check()
        global_tree = self._stack_local(tree)
        out = self._executable(global_tree)(global_tree)
        self._wait_ready(out)
        return self._local_row(out)

    def _wait_ready(self, tree: Any) -> None:
        """Poll the dispatched collective's buffers instead of blocking on
        them.  Load-bearing for detection SYMMETRY: when a member dies
        mid-collective, only its gloo-ring neighbor gets an instant
        connection-reset — a non-adjacent survivor's op simply never
        completes, and a thread blocked inside gloo cannot be interrupted.
        Dispatch is async (the CPU client delivers failures through buffer
        definition events), so the main thread polls ``is_ready`` with the
        TTL probe in between: every survivor surfaces the death as
        ``WorldChanged`` within one TTL, whatever its ring position.  The
        abandoned op stays pending inside the dead world's client, which
        is leaked by design — joining its execute thread would block
        forever (one dangling client per resize, bounded by
        ``max_rounds``)."""
        import time as _time

        import jax

        pending = list(jax.tree.leaves(tree))
        deadline = _time.monotonic() + self.timeout_s
        # the readiness poll is 2 ms, but the membership probe is a coord-
        # store RPC — rate-limit it so a long collective doesn't hammer
        # the control plane (one live() per ~100 ms is far inside the TTL)
        next_check = 0.0
        while True:
            pending = [leaf for leaf in pending if not leaf.is_ready()]
            if not pending:
                return
            now = _time.monotonic()
            if self.on_check is not None and now >= next_check:
                self.on_check()
                next_check = now + 0.1
            if _time.monotonic() > deadline:
                from tpudist.runtime.collectives import PeerLost

                raise PeerLost(
                    f"ici collective not ready within {self.timeout_s}s "
                    f"at world {self.world}")
            _time.sleep(0.002)

    def allreduce_sum(self, tree: Any) -> Any:
        mean = self.allreduce_mean(tree)
        import jax

        return jax.tree.map(lambda x: x * self.num_processes, mean)

    # -- reduce-scatter / all-gather primitives ------------------------------
    # True primitives (jax.lax.psum_scatter / all_gather inside the same
    # compiled shard_map shell as the pmean) — NOT all-gather-and-reduce.
    # They are the intra-host legs of HostCollectives' algorithm="hier"
    # (see IciIntraHost below): each member process holds one flat
    # vector; reduce_scatter leaves it with its shard of the sum, the
    # cross-host ring reduces that shard over the store, and all_gather
    # rebuilds the full vector from the finished shards.

    def rs_bounds(self, n: int) -> list[tuple[int, int]]:
        """Per-PROCESS shard boundaries used by :meth:`reduce_scatter` /
        :meth:`all_gather` for a vector of ``n`` elements: equal padded
        shards of ``ceil(n/world_devices) × local_rows`` elements
        (psum_scatter tiles per DEVICE, so the per-process shard must be
        a whole number of device tiles), clamped to ``[0, n)``.
        Identical on every member — the hier dispatcher uses these as
        the shard map."""
        q = -(-n // self.world) if n else 0
        per = q * self.local_rows
        return [(min(n, p * per), min(n, (p + 1) * per))
                for p in range(self.num_processes)]

    def _rs_executable(self, kind: str, shape: tuple, dtype: Any) -> Any:
        import jax

        key = (kind, shape, str(dtype))
        exe = self._execs.get(key)
        if exe is None:
            from tpudist.obs.xla import compile_watch

            spec = jax.sharding.PartitionSpec(self.axis)
            if kind == "rs":
                def body(x):  # per device (1, npad) -> (1, q)
                    return jax.lax.psum_scatter(
                        x, self.axis, scatter_dimension=1, tiled=True)

                out_spec = spec
            else:
                def body(x):  # per device (1, q) -> replicated (world, q)
                    return jax.lax.all_gather(
                        x, self.axis, axis=0, tiled=True)

                out_spec = jax.sharding.PartitionSpec()
            # check_rep can't statically infer that a tiled all-gather's
            # output is replicated; the gather itself guarantees it
            fn = jax.jit(jax.shard_map(
                body, mesh=self.mesh, in_specs=spec, out_specs=out_spec,
                check_rep=(kind == "rs")))
            arg = jax.ShapeDtypeStruct(shape, dtype, sharding=self._sharding)
            with compile_watch("ici"):
                exe = fn.lower(arg).compile()
            self._execs[key] = exe
            self.last_hlo = exe.as_text()
        return exe

    def _local_rows_of(self, out: Any) -> list[np.ndarray]:
        """This process's device rows of a dim-0-sharded array, in global
        row order, asserted contiguous (the formed mesh orders devices by
        process, which is what makes a process's shard one contiguous
        slice)."""
        rows = sorted(
            ((s.index[0].start or 0, np.asarray(s.data))
             for s in out.addressable_shards),
            key=lambda t: t[0])
        starts = [r for r, _ in rows]
        if starts != list(range(starts[0], starts[0] + len(starts))):
            raise RuntimeError(
                f"process device rows not contiguous in mesh: {starts}")
        return [d.reshape(-1) for _, d in rows]

    def reduce_scatter(self, vec: np.ndarray) -> np.ndarray:
        """SUM-reduce an identical-length 1-D vector across member
        processes and return only this process's :meth:`rs_bounds` shard
        — one compiled ``reduce-scatter``, moving ``(world-1)/world`` of
        the bytes an all-reduce would.  Exactness: only each process's
        FIRST device row carries the payload (the rest contribute
        zeros), so the device-level sum equals the process-level sum
        with no replication scaling to divide away."""
        import jax

        if self.on_check is not None:
            self.on_check()
        vec = np.asarray(vec)
        n = vec.size
        lo, hi = self.rs_bounds(n)[jax.process_index()]
        if n == 0:
            return np.empty(0, vec.dtype)
        q = -(-n // self.world)
        npad = q * self.world
        local = np.zeros((self.local_rows, npad), dtype=vec.dtype)
        local[0, :n] = vec
        garr = jax.make_array_from_process_local_data(
            self._sharding, local, (self.world, npad))
        out = self._rs_executable("rs", (self.world, npad), vec.dtype)(garr)
        self._wait_ready(out)
        full = np.concatenate(self._local_rows_of(out))
        return full[:hi - lo]

    def all_gather(self, shard: np.ndarray, n: int) -> np.ndarray:
        """Inverse of :meth:`reduce_scatter`: every process contributes
        its ``rs_bounds`` shard of a length-``n`` vector and receives the
        whole vector — one compiled ``all-gather``, no arithmetic, so
        the result is bitwise the concatenation of the posted shards on
        every member."""
        import jax

        if self.on_check is not None:
            self.on_check()
        shard = np.asarray(shard)
        bounds = self.rs_bounds(n)
        lo, hi = bounds[jax.process_index()]
        if shard.size != hi - lo:
            raise ValueError(
                f"shard has {shard.size} elements; rs_bounds expects "
                f"{hi - lo} for process {jax.process_index()} of n={n}")
        if n == 0:
            return np.empty(0, shard.dtype)
        q = -(-n // self.world)
        per = q * self.local_rows
        buf = np.zeros(per, dtype=shard.dtype)
        buf[:shard.size] = shard
        local = buf.reshape(self.local_rows, q)
        garr = jax.make_array_from_process_local_data(
            self._sharding, local, (self.world, q))
        out = self._rs_executable("ag", (self.world, q), shard.dtype)(garr)
        self._wait_ready(out)
        flat = np.asarray(out.addressable_shards[0].data) \
            .reshape(self.num_processes, per)
        return np.concatenate([
            flat[p, :bounds[p][1] - bounds[p][0]]
            for p in range(self.num_processes)])

    # -- async handles ------------------------------------------------------
    # XLA dispatch is ALREADY asynchronous (the executable call returns
    # before the collective completes; _wait_ready polls afterwards), so
    # the async API here needs no worker thread: submit dispatches on the
    # caller's thread and the Handle defers only the readiness wait +
    # local-row extraction.  Same contract as
    # HostCollectives.allreduce_*_async — submit, overlap host work, wait.

    def allreduce_mean_async(self, tree: Any) -> "IciAsyncHandle":
        if self.on_check is not None:
            self.on_check()
        global_tree = self._stack_local(tree)
        out = self._executable(global_tree)(global_tree)
        return IciAsyncHandle(self, out, scale=1.0)

    def allreduce_sum_async(self, tree: Any) -> "IciAsyncHandle":
        h = self.allreduce_mean_async(tree)
        h.scale = float(self.num_processes)
        return h

    def _local_row(self, out_tree: Any) -> Any:
        import jax

        return jax.tree.map(
            lambda a: np.asarray(a.addressable_shards[0].data)[0], out_tree)


class IciAsyncHandle:
    """In-flight compiled allreduce: the op was dispatched at submit time;
    :meth:`wait` polls it ready (same TTL-probing poll as the sync path,
    so a peer death still surfaces as ``WorldChanged``/``PeerLost`` from
    ``wait()``) and returns this process's reduced row."""

    def __init__(self, coll: IciCollectives, out_tree: Any,
                 scale: float) -> None:
        self._coll = coll
        self._out = out_tree
        self.scale = scale

    def done(self) -> bool:
        import jax

        return all(leaf.is_ready() for leaf in jax.tree.leaves(self._out))

    def wait(self, timeout_s: float | None = None) -> Any:
        import jax

        self._coll._wait_ready(self._out)
        row = self._coll._local_row(self._out)
        if self.scale != 1.0:
            row = jax.tree.map(lambda x: x * self.scale, row)
        return row


class IciIntraHost:
    """Adapter presenting an :class:`IciCollectives` that spans ONE
    host's member processes as the intra-host plane of
    ``HostCollectives(config=CollectiveConfig(algorithm="hier"), intra=...)``.

    With it, hier's phase 1 and phase 3 ride the compiled ICI
    reduce-scatter/all-gather instead of the coord store, and only the
    per-shard cross-host ring touches the store — the deployment shape
    the hierarchical algorithm exists for.  Requirements checked by the
    dispatcher: the wrapped mesh's process group must be exactly this
    rank's host group (``local_world == world // hosts``, host-contiguous
    rank numbering), which is how the launcher numbers a gang.

    The protocol (duck-typed by :class:`HostCollectives`):
    ``local_world`` / ``local_index`` attributes, ``bounds(n)`` for the
    shard map, ``reduce_scatter(vec)`` → this process's shard of the
    host sum, ``all_gather(shard, n)`` → the full host vector."""

    def __init__(self, coll: IciCollectives) -> None:
        import jax

        self._coll = coll
        self.local_world = coll.num_processes
        self.local_index = jax.process_index()

    def bounds(self, n: int) -> list[tuple[int, int]]:
        return self._coll.rs_bounds(n)

    def reduce_scatter(self, vec: np.ndarray) -> np.ndarray:
        return self._coll.reduce_scatter(vec)

    def all_gather(self, shard: np.ndarray, n: int) -> np.ndarray:
        return self._coll.all_gather(shard, n)
