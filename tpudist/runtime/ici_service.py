"""Standalone host for the per-round ``jax.distributed`` coordination
service (run as ``python -m tpudist.runtime.ici_service``).

The coordination service bootstraps each elastic round's PJRT world
(:mod:`tpudist.runtime.ici`).  Hosting it INSIDE a worker — the
``jax.distributed.initialize`` default, where rank 0 is the leader — is
fatal to elasticity: a coordination client whose leader becomes
unreachable terminates its own process (the agent's error-poll /
missed-heartbeat handlers end in ``LOG(FATAL)``, and the Python
``missed_heartbeat_callback`` binding aborts on invocation), so the
round's leader dying would take every survivor down with it.  Running the
service in this tiny dedicated process — the same role torchrun gives a
standalone c10d/etcd rendezvous host — means no worker is ever the
leader: members always have a live service to disconnect from cleanly,
whatever happened to their peers.

Lifecycle: spawned (detached) by the round's rank 0, killed by a later
round's rank 0 once the round is two generations stale, or at end of run
(`IciDataPlane.finalize`).  Exits on SIGTERM; also self-expires after
``--max-lifetime-s`` as a leak backstop.
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--heartbeat-timeout-s", type=int, default=86400)
    ap.add_argument("--max-lifetime-s", type=float, default=3600.0)
    args = ap.parse_args(argv)

    from jax._src.lib import _jax as _jaxlib

    service = _jaxlib.get_distributed_runtime_service(
        f"[::]:{args.port}", args.world,
        heartbeat_timeout=args.heartbeat_timeout_s,
        shutdown_timeout=5)
    print("ready", flush=True)

    stop = {"flag": False}

    def on_term(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    deadline = time.monotonic() + args.max_lifetime_s
    while not stop["flag"] and time.monotonic() < deadline:
        time.sleep(0.2)
    del service  # all clients have disconnected by the time we're killed
    return 0


if __name__ == "__main__":
    sys.exit(main())
