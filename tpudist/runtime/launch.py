"""Multi-process launcher — the torchrun / horovodrun / ``mp.spawn`` twin.

The reference boots its worlds three ways (SURVEY.md §1 L5): ``torchrun``
with c10d rendezvous + restart-on-failure (`mnist_ddp_elastic.py:5-6`),
``horovodrun`` over MPI (`horovod_mnist_elastic.py:108`), and in-process
``torch.multiprocessing.spawn`` (`model_parallel_ResNet50.py:257-260`).  On
TPU pods the platform launches one process per host, so in production none
of this is needed — but the *capability* (spawn an N-process world on one
machine, supervise it, restart the gang on failure) is what makes
multi-host code testable without a pod.  ``python -m tpudist.runtime.launch``
provides it:

* spawns ``-n`` worker processes, each with ``TPUDIST_COORDINATOR`` /
  ``TPUDIST_NUM_PROCESSES`` / ``TPUDIST_PROCESS_ID`` env vars that
  :func:`tpudist.runtime.distributed.initialize` consumes (the
  RANK/WORLD_SIZE contract, `mnist_ddp_elastic.py:44-45`),
* starts the native coordination service and exports ``TPUDIST_COORD_ADDR``
  so workers can rendezvous / heartbeat through it,
* supervises the gang: if any worker dies, the rest are terminated and the
  whole gang restarts (TorchElastic semantics), up to ``--max-restarts``.

Workers default to the CPU backend (each process owns a slice of a
simulated world) — real TPU jobs don't go through this launcher.
"""

from __future__ import annotations

import argparse
import os
import random
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

from tpudist.utils.logging import get_logger

log = get_logger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _discover_world_size(discover_cmd: str, current: int, lo: int,
                         hi: int) -> int:
    """Run the discovery command; its stdout (an int) is the next world
    size, clamped to [lo, hi].  Failures keep the current size (a broken
    discovery script must not take the job down)."""
    try:
        out = subprocess.run(
            discover_cmd, shell=True, capture_output=True, text=True,
            timeout=30, check=True,
        ).stdout.strip()
        return min(hi, max(lo, int(out.splitlines()[-1])))
    except Exception as e:  # noqa: BLE001 - discovery is advisory
        stderr = getattr(e, "stderr", None)
        log.warning("discovery command failed (%s)%s; keeping world=%d",
                    e, f": {stderr.strip()}" if stderr else "", current)
        return current


def launch(
    cmd: list[str],
    nprocs: int,
    max_restarts: int = 0,
    env: dict | None = None,
    platform: str = "cpu",
    devices_per_proc: int = 1,
    coord_server: bool = True,
    min_nprocs: int | None = None,
    restart_cooldown: tuple[float, float] | float | None = None,
    discover_cmd: str | None = None,
    elastic_inprocess: bool = False,
) -> int:
    """Run ``cmd`` as an ``nprocs``-process gang; returns the gang's exit
    code (0 only if every worker of some attempt exited 0).

    Elastic restarts (the ``horovodrun --min-np/--host-discovery-script/
    --blacklist-cooldown-range`` surface, `horovod_mnist_elastic.py:108`):

    * ``min_nprocs`` — after a failed attempt the gang restarts one worker
      SMALLER (a persistently failing member is dropped, Horovod's
      blacklist effect), never below this floor; workers see the new size
      in ``TPUDIST_NUM_PROCESSES`` and rescale via their reset callbacks.
    * ``discover_cmd`` — shell command run before each restart whose stdout
      (an integer) sets the next world size, clamped to
      ``[min_nprocs or 1, nprocs]`` (≙ ``--host-discovery-script``).
    * ``restart_cooldown`` — seconds (or a ``(lo, hi)`` range sampled
      uniformly) to wait before each restart (≙ the blacklist cooldown).
    * ``elastic_inprocess`` — a dying worker does NOT tear the gang down:
      survivors are expected to detect the loss themselves via coordination-
      service TTL heartbeats and re-rendezvous smaller in-process
      (:func:`tpudist.elastic.worker.run_elastic_worker` — the Horovod
      elastic-driver model, vs. the default torchrun gang-restart model).
      The attempt succeeds when at least ``min_nprocs or 1`` workers exit 0.
    """
    if min_nprocs is not None and min_nprocs > nprocs:
        raise ValueError(
            f"min_nprocs ({min_nprocs}) must not exceed nprocs ({nprocs})")
    server = None
    base_env = dict(os.environ)
    # Workers must resolve the same tpudist the launcher runs from, however
    # the launcher itself was put on sys.path (pytest rootdir, pip -e, ...).
    pkg_root = str(Path(__file__).resolve().parents[2])
    base_env["PYTHONPATH"] = os.pathsep.join(
        [pkg_root] + ([base_env["PYTHONPATH"]] if base_env.get("PYTHONPATH") else [])
    )
    if env:
        base_env.update(env)
    if coord_server:
        try:
            from tpudist.runtime.coord import CoordServer

            server = CoordServer(0)
            base_env["TPUDIST_COORD_ADDR"] = f"127.0.0.1:{server.port}"
        except Exception as e:  # noqa: BLE001 - control plane is optional
            log.warning("coordination server unavailable (%s); continuing", e)

    world = nprocs
    floor = max(1, min_nprocs) if min_nprocs else None
    try:
        for attempt in range(max_restarts + 1):
            if attempt > 0:
                if restart_cooldown is not None:
                    lo, hi = (restart_cooldown if isinstance(
                        restart_cooldown, tuple) else (restart_cooldown,) * 2)
                    time.sleep(random.uniform(lo, hi))
                if discover_cmd is not None:
                    world = _discover_world_size(
                        discover_cmd, world, floor or 1, nprocs)
                elif floor is not None:
                    world = max(floor, world - 1)
            coordinator = f"127.0.0.1:{_free_port()}"
            procs: list[subprocess.Popen] = []
            for rank in range(world):
                wenv = dict(base_env)
                wenv.update({
                    "TPUDIST_COORDINATOR": coordinator,
                    "TPUDIST_NUM_PROCESSES": str(world),
                    "TPUDIST_PROCESS_ID": str(rank),
                    "TPUDIST_LOCAL_RANK": str(rank),
                    "TPUDIST_RESTART_ATTEMPT": str(attempt),
                })
                if platform:
                    wenv["JAX_PLATFORMS"] = platform
                    if platform == "cpu":
                        # Each worker owns exactly devices_per_proc simulated
                        # devices; drop any inherited host-device-count flag.
                        flags = [f for f in wenv.get("XLA_FLAGS", "").split()
                                 if not f.startswith(
                                     "--xla_force_host_platform_device_count")]
                        flags.append("--xla_force_host_platform_device_count"
                                     f"={devices_per_proc}")
                        wenv["XLA_FLAGS"] = " ".join(flags)
                procs.append(subprocess.Popen(cmd, env=wenv))
            codes = _supervise(procs, tear_down=not elastic_inprocess)
            if elastic_inprocess:
                if sum(c == 0 for c in codes) >= (floor or 1):
                    return 0
            elif all(c == 0 for c in codes):
                return 0
            log.warning(
                "gang attempt %d failed (exit codes %s)%s", attempt, codes,
                "; restarting" if attempt < max_restarts else "",
            )
        # Survivors torn down by _supervise exit with the termination
        # signal; report the code of the worker that actually failed.
        failing = [c for c in codes
                   if c not in (0, -signal.SIGTERM, -signal.SIGKILL)]
        return failing[0] if failing else next(c for c in codes if c != 0)
    finally:
        if server is not None:
            server.stop()


def _supervise(procs: list[subprocess.Popen],
               tear_down: bool = True) -> list[int]:
    """Wait for the gang; on first failure, terminate the survivors (the
    torchrun gang-failure contract).  With ``tear_down=False`` a failure
    leaves the survivors running (in-process elastic: they shrink the world
    themselves via TTL rendezvous)."""
    try:
        while True:
            codes = [p.poll() for p in procs]
            if all(c is not None for c in codes):
                return codes  # type: ignore[return-value]
            if tear_down and any(c not in (None, 0) for c in codes):
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                deadline = time.monotonic() + 10
                for p in procs:
                    timeout = max(0.1, deadline - time.monotonic())
                    try:
                        p.wait(timeout=timeout)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                return [p.returncode for p in procs]
            time.sleep(0.05)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        raise


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudist.runtime.launch",
        description="Spawn and supervise an N-process tpudist world on this host",
    )
    ap.add_argument("-n", "--nprocs", type=int, required=True)
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="gang restarts on worker failure (torchrun semantics)")
    ap.add_argument("--platform", default="cpu",
                    help="JAX_PLATFORMS for workers ('' = inherit)")
    ap.add_argument("--devices-per-proc", type=int, default=1,
                    help="simulated CPU devices per worker")
    ap.add_argument("--min-nprocs", type=int, default=None,
                    help="shrink the gang toward this floor on repeated "
                         "failure (horovodrun --min-np semantics)")
    ap.add_argument("--restart-cooldown", default=None,
                    help="seconds before each restart, or LO:HI range "
                         "(horovodrun --blacklist-cooldown-range)")
    ap.add_argument("--discover-cmd", default=None,
                    help="shell command printing the next world size "
                         "(horovodrun --host-discovery-script)")
    ap.add_argument("--no-coord", action="store_true",
                    help="skip the native coordination server")
    ap.add_argument("--elastic-inprocess", action="store_true",
                    help="don't tear the gang down on a worker death; "
                         "survivors shrink via TTL rendezvous "
                         "(tpudist.elastic.worker)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command, e.g. script.py arg1 arg2")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("missing worker command")
    if cmd[0].endswith(".py"):
        cmd = [sys.executable, *cmd]
    cooldown = None
    if args.restart_cooldown is not None:
        try:
            parts = [float(v) for v in str(args.restart_cooldown).split(":")]
        except ValueError:
            ap.error(f"--restart-cooldown must be SECONDS or LO:HI, got "
                     f"{args.restart_cooldown!r}")
        if len(parts) > 2:
            ap.error(f"--restart-cooldown must be SECONDS or LO:HI, got "
                     f"{args.restart_cooldown!r}")
        if any(p < 0 for p in parts):
            ap.error("--restart-cooldown values must be non-negative")
        cooldown = (parts[0], parts[1]) if len(parts) == 2 else parts[0]
    if args.min_nprocs is not None and args.min_nprocs > args.nprocs:
        ap.error(f"--min-nprocs ({args.min_nprocs}) must not exceed "
                 f"-n ({args.nprocs})")
    return launch(
        cmd, args.nprocs, max_restarts=args.max_restarts,
        platform=args.platform, devices_per_proc=args.devices_per_proc,
        coord_server=not args.no_coord, min_nprocs=args.min_nprocs,
        restart_cooldown=cooldown, discover_cmd=args.discover_cmd,
        elastic_inprocess=args.elastic_inprocess,
    )


if __name__ == "__main__":
    sys.exit(main())
