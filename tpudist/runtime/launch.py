"""Multi-process launcher — the torchrun / horovodrun / ``mp.spawn`` twin.

The reference boots its worlds three ways (SURVEY.md §1 L5): ``torchrun``
with c10d rendezvous + restart-on-failure (`mnist_ddp_elastic.py:5-6`),
``horovodrun`` over MPI (`horovod_mnist_elastic.py:108`), and in-process
``torch.multiprocessing.spawn`` (`model_parallel_ResNet50.py:257-260`).  On
TPU pods the platform launches one process per host, so in production none
of this is needed — but the *capability* (spawn an N-process world on one
machine, supervise it, restart the gang on failure) is what makes
multi-host code testable without a pod.  ``python -m tpudist.runtime.launch``
provides it:

* spawns ``-n`` worker processes, each with ``TPUDIST_COORDINATOR`` /
  ``TPUDIST_NUM_PROCESSES`` / ``TPUDIST_PROCESS_ID`` env vars that
  :func:`tpudist.runtime.distributed.initialize` consumes (the
  RANK/WORLD_SIZE contract, `mnist_ddp_elastic.py:44-45`),
* starts the native coordination service and exports ``TPUDIST_COORD_ADDR``
  so workers can rendezvous / heartbeat through it,
* supervises the gang: if any worker dies, the rest are terminated and the
  whole gang restarts (TorchElastic semantics), up to ``--max-restarts``.

Workers default to the CPU backend (each process owns a slice of a
simulated world) — real TPU jobs don't go through this launcher.
"""

from __future__ import annotations

import argparse
import os
import random
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

from tpudist import obs
from tpudist.utils.logging import get_logger

log = get_logger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _discover_world_size(discover_cmd: str, current: int, lo: int,
                         hi: int) -> int:
    """Run the discovery command; its stdout (an int) is the next world
    size, clamped to [lo, hi].  Failures keep the current size (a broken
    discovery script must not take the job down)."""
    try:
        out = subprocess.run(
            discover_cmd, shell=True, capture_output=True, text=True,
            timeout=30, check=True,
        ).stdout.strip()
        return min(hi, max(lo, int(out.splitlines()[-1])))
    except Exception as e:  # noqa: BLE001 - discovery is advisory
        stderr = getattr(e, "stderr", None)
        log.warning("discovery command failed (%s)%s; keeping world=%d",
                    e, f": {stderr.strip()}" if stderr else "", current)
        return current


def launch(
    cmd: list[str],
    nprocs: int,
    max_restarts: int = 0,
    env: dict | None = None,
    platform: str = "cpu",
    devices_per_proc: int = 1,
    coord_server: bool = True,
    min_nprocs: int | None = None,
    restart_cooldown: tuple[float, float] | float | None = None,
    discover_cmd: str | None = None,
    elastic_inprocess: bool = False,
    blacklist_after: int | None = None,
    blacklist_cooldown: tuple[float, float] | float | None = None,
) -> int:
    """Run ``cmd`` as an ``nprocs``-process gang; returns the gang's exit
    code (0 only if every worker of some attempt exited 0).

    Elastic restarts (the ``horovodrun --min-np/--host-discovery-script/
    --blacklist-cooldown-range`` surface, `horovod_mnist_elastic.py:108`):

    * ``min_nprocs`` — after a failed attempt the gang restarts one worker
      SMALLER (a persistently failing member is dropped, Horovod's
      blacklist effect), never below this floor; workers see the new size
      in ``TPUDIST_NUM_PROCESSES`` and rescale via their reset callbacks.
    * ``discover_cmd`` — shell command run before each restart whose stdout
      (an integer) sets the next world size, clamped to
      ``[min_nprocs or 1, nprocs]`` (≙ ``--host-discovery-script``).
    * ``restart_cooldown`` — seconds (or a ``(lo, hi)`` range sampled
      uniformly) to wait before each restart.
    * ``blacklist_after`` — PER-WORKER blacklist (Horovod's actual
      per-host semantics: the SPECIFIC failing host is excluded, healthy
      ones keep their place).  Every spawn slot carries a stable
      ``TPUDIST_SPAWN_ID`` across attempts; a slot whose worker exits
      nonzero in ``blacklist_after`` attempts is excluded from the roster
      and a FRESH spawn id re-grows the world (≙ a replacement host from
      discovery), while healthy slots are never the ones dropped.
    * ``blacklist_cooldown`` — seconds (or ``(lo, hi)`` sampled) until a
      blacklisted slot may rejoin the roster with its failure count reset
      (``--blacklist-cooldown-range``); ``None`` = excluded for the rest
      of this launch.
    * ``elastic_inprocess`` — a dying worker does NOT tear the gang down:
      survivors are expected to detect the loss themselves via coordination-
      service TTL heartbeats and re-rendezvous smaller in-process
      (:func:`tpudist.elastic.worker.run_elastic_worker` — the Horovod
      elastic-driver model, vs. the default torchrun gang-restart model).
      The attempt succeeds when at least ``min_nprocs or 1`` workers exit 0.
    """
    if min_nprocs is not None and min_nprocs > nprocs:
        raise ValueError(
            f"min_nprocs ({min_nprocs}) must not exceed nprocs ({nprocs})")
    if blacklist_after is not None and blacklist_after < 1:
        raise ValueError(
            f"blacklist_after must be >= 1, got {blacklist_after}")
    server = None
    watcher = None
    base_env = dict(os.environ)
    # Workers must resolve the same tpudist the launcher runs from, however
    # the launcher itself was put on sys.path (pytest rootdir, pip -e, ...).
    pkg_root = str(Path(__file__).resolve().parents[2])
    base_env["PYTHONPATH"] = os.pathsep.join(
        [pkg_root] + ([base_env["PYTHONPATH"]] if base_env.get("PYTHONPATH") else [])
    )
    if env:
        base_env.update(env)
    if coord_server:
        try:
            from tpudist.runtime.coord import CoordServer

            server = CoordServer(0)
            base_env["TPUDIST_COORD_ADDR"] = f"127.0.0.1:{server.port}"
            # the health plane rides the same store the workers publish
            # metrics through: the watcher classifies stragglers/stale
            # ranks so supervision decisions below can cite evidence
            try:
                from tpudist.obs.health import HealthWatcher

                watcher = HealthWatcher(base_env["TPUDIST_COORD_ADDR"])
            except Exception as e:  # noqa: BLE001 - health is advisory
                log.warning("health watcher unavailable (%s); continuing", e)
        except Exception as e:  # noqa: BLE001 - control plane is optional
            log.warning("coordination server unavailable (%s); continuing", e)

    world = nprocs
    floor = max(1, min_nprocs) if min_nprocs else None

    def _sample(cool):
        lo, hi = cool if isinstance(cool, tuple) else (cool,) * 2
        return random.uniform(lo, hi)

    # per-spawn-slot failure ledger (blacklist_after mode): slots carry a
    # stable spawn id across attempts; repeat offenders are excluded and
    # replaced by FRESH ids so healthy workers keep their place
    roster = list(range(nprocs))
    next_sid = nprocs
    fail_counts: dict[int, int] = {}
    black_until: dict[int, float] = {}
    try:
        for attempt in range(max_restarts + 1):
            if attempt > 0:
                if restart_cooldown is not None:
                    time.sleep(_sample(restart_cooldown))
                if discover_cmd is not None:
                    world = _discover_world_size(
                        discover_cmd, world, floor or 1, nprocs)
                elif floor is not None and blacklist_after is None:
                    world = max(floor, world - 1)
                obs.counter("launch/restarts").inc()
                if blacklist_after is not None:
                    now = time.monotonic()
                    for sid, until in list(black_until.items()):
                        if until <= now:   # cooled down: eligible again
                            del black_until[sid]
                            fail_counts.pop(sid, None)
                            # Rejoin AHEAD of synthetic replacement slots
                            # (sids >= nprocs): the scheduled set is
                            # roster[:world], so a tail append would park
                            # the recovered slot behind the fresh sids
                            # that replaced it — cooled down yet never
                            # scheduled again.  Original slots keep their
                            # relative order; replacements only fill
                            # whatever room is left.
                            insert_at = next(
                                (i for i, s in enumerate(roster)
                                 if s >= nprocs), len(roster))
                            roster.insert(insert_at, sid)
                            obs.counter("launch/blacklist_recovered").inc()
                    for sid in list(roster):
                        if fail_counts.get(sid, 0) >= blacklist_after:
                            black_until[sid] = (
                                now + _sample(blacklist_cooldown)
                                if blacklist_cooldown is not None
                                else float("inf"))
                            roster.remove(sid)
                            obs.counter("launch/blacklisted").inc()
                            log.warning(
                                "spawn id %d blacklisted after %d failed "
                                "attempts%s%s", sid, fail_counts[sid],
                                "" if blacklist_cooldown is None else
                                f" (cooldown until +{black_until[sid] - now:.1f}s)",
                                f" [{watcher.describe()}]" if watcher else "")
                    while len(roster) < world:
                        roster.append(next_sid)   # fresh replacement slot
                        next_sid += 1
            ids = (roster[:world] if blacklist_after is not None
                   else list(range(world)))
            coordinator = f"127.0.0.1:{_free_port()}"
            procs: list[subprocess.Popen] = []
            for rank in range(world):
                wenv = dict(base_env)
                wenv.update({
                    "TPUDIST_COORDINATOR": coordinator,
                    "TPUDIST_NUM_PROCESSES": str(world),
                    "TPUDIST_PROCESS_ID": str(rank),
                    "TPUDIST_LOCAL_RANK": str(rank),
                    "TPUDIST_SPAWN_ID": str(ids[rank]),
                    "TPUDIST_RESTART_ATTEMPT": str(attempt),
                })
                if platform:
                    wenv["JAX_PLATFORMS"] = platform
                    if platform == "cpu":
                        # Each worker owns exactly devices_per_proc simulated
                        # devices; drop any inherited host-device-count flag.
                        flags = [f for f in wenv.get("XLA_FLAGS", "").split()
                                 if not f.startswith(
                                     "--xla_force_host_platform_device_count")]
                        flags.append("--xla_force_host_platform_device_count"
                                     f"={devices_per_proc}")
                        wenv["XLA_FLAGS"] = " ".join(flags)
                procs.append(subprocess.Popen(cmd, env=wenv))
            codes = _supervise(procs, tear_down=not elastic_inprocess)
            if blacklist_after is not None:
                # charge the ACTUAL failers, not supervisor-terminated
                # survivors (they exit -SIGTERM; a straggler escalated to
                # SIGKILL is indistinguishable from a kill -9 death and is
                # charged — acceptable: it failed to exit cleanly)
                for sid, code in zip(ids, codes):
                    if code not in (0, -signal.SIGTERM):
                        fail_counts[sid] = fail_counts.get(sid, 0) + 1
                        obs.counter("launch/worker_failures").inc()
            if elastic_inprocess:
                if sum(c == 0 for c in codes) >= (floor or 1):
                    return 0
            elif all(c == 0 for c in codes):
                return 0
            log.warning(
                "gang attempt %d failed (exit codes %s)%s%s", attempt, codes,
                "; restarting" if attempt < max_restarts else "",
                f" [{watcher.describe()}]" if watcher else "",
            )
        # Survivors torn down by _supervise exit with the termination
        # signal; report the code of the worker that actually failed.
        failing = [c for c in codes
                   if c not in (0, -signal.SIGTERM, -signal.SIGKILL)]
        return failing[0] if failing else next(c for c in codes if c != 0)
    finally:
        if watcher is not None:
            try:
                watcher.stop()
            except Exception:  # noqa: BLE001 - advisory plane
                pass
        if server is not None:
            server.stop()


def _supervise(procs: list[subprocess.Popen],
               tear_down: bool = True) -> list[int]:
    """Wait for the gang; on first failure, terminate the survivors (the
    torchrun gang-failure contract).  With ``tear_down=False`` a failure
    leaves the survivors running (in-process elastic: they shrink the world
    themselves via TTL rendezvous)."""
    try:
        while True:
            codes = [p.poll() for p in procs]
            if all(c is not None for c in codes):
                return codes  # type: ignore[return-value]
            if tear_down and any(c not in (None, 0) for c in codes):
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                deadline = time.monotonic() + 10
                for p in procs:
                    timeout = max(0.1, deadline - time.monotonic())
                    try:
                        p.wait(timeout=timeout)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                return [p.returncode for p in procs]
            time.sleep(0.05)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        raise


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudist.runtime.launch",
        description="Spawn and supervise an N-process tpudist world on this host",
    )
    ap.add_argument("-n", "--nprocs", type=int, required=True)
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="gang restarts on worker failure (torchrun semantics)")
    ap.add_argument("--platform", default="cpu",
                    help="JAX_PLATFORMS for workers ('' = inherit)")
    ap.add_argument("--devices-per-proc", type=int, default=1,
                    help="simulated CPU devices per worker")
    ap.add_argument("--min-nprocs", type=int, default=None,
                    help="shrink the gang toward this floor on repeated "
                         "failure (horovodrun --min-np semantics)")
    ap.add_argument("--restart-cooldown", default=None,
                    help="seconds before each restart, or LO:HI range")
    ap.add_argument("--blacklist-after", type=int, default=None,
                    help="exclude a spawn slot after this many failed "
                         "attempts, re-growing the world with a fresh "
                         "slot (per-host blacklist semantics)")
    ap.add_argument("--blacklist-cooldown", default=None,
                    help="seconds (or LO:HI range) until a blacklisted "
                         "slot may rejoin (horovodrun "
                         "--blacklist-cooldown-range); default: excluded "
                         "for the rest of the run")
    ap.add_argument("--discover-cmd", default=None,
                    help="shell command printing the next world size "
                         "(horovodrun --host-discovery-script)")
    ap.add_argument("--no-coord", action="store_true",
                    help="skip the native coordination server")
    ap.add_argument("--elastic-inprocess", action="store_true",
                    help="don't tear the gang down on a worker death; "
                         "survivors shrink via TTL rendezvous "
                         "(tpudist.elastic.worker)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command, e.g. script.py arg1 arg2")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("missing worker command")
    if cmd[0].endswith(".py"):
        cmd = [sys.executable, *cmd]
    def parse_cooldown(value, flag):
        if value is None:
            return None
        try:
            parts = [float(v) for v in str(value).split(":")]
        except ValueError:
            ap.error(f"{flag} must be SECONDS or LO:HI, got {value!r}")
        if len(parts) > 2:
            ap.error(f"{flag} must be SECONDS or LO:HI, got {value!r}")
        if any(p < 0 for p in parts):
            ap.error(f"{flag} values must be non-negative")
        return (parts[0], parts[1]) if len(parts) == 2 else parts[0]

    cooldown = parse_cooldown(args.restart_cooldown, "--restart-cooldown")
    bl_cooldown = parse_cooldown(args.blacklist_cooldown,
                                 "--blacklist-cooldown")
    if args.min_nprocs is not None and args.min_nprocs > args.nprocs:
        ap.error(f"--min-nprocs ({args.min_nprocs}) must not exceed "
                 f"-n ({args.nprocs})")
    return launch(
        cmd, args.nprocs, max_restarts=args.max_restarts,
        platform=args.platform, devices_per_proc=args.devices_per_proc,
        coord_server=not args.no_coord, min_nprocs=args.min_nprocs,
        restart_cooldown=cooldown, discover_cmd=args.discover_cmd,
        elastic_inprocess=args.elastic_inprocess,
        blacklist_after=args.blacklist_after,
        blacklist_cooldown=bl_cooldown,
    )


if __name__ == "__main__":
    sys.exit(main())
