"""Device-mesh construction.

The mesh is the single abstraction that replaces the reference's three
communication substrates (gloo groups `mnist_ddp_elastic.py:26`, Horovod ring
`mnist_horovod.py:28`, TensorPipe RPC mesh `model_parallel_ResNet50.py:233-249`
— SURVEY.md §1 L1).  Every parallelism strategy in tpudist is expressed as
shardings over named mesh axes:

* ``data``  — batch axis; gradient psum rides ICI (DDP / Horovod equivalent)
* ``stage`` — pipeline axis; activations travel via ppermute (RPC pipeline
  equivalent)
* ``model`` — parameter-sharding axis (parameter-server / tensor sharding)

Meshes are ordinary :class:`jax.sharding.Mesh` objects; helpers here only
decide the device grid layout so that the fastest-varying axis maps to
physically adjacent chips (ICI-friendly).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def get_devices(n: int | None = None, backend: str | None = None) -> list[jax.Device]:
    """Return the first ``n`` jax devices (all if ``n`` is None).

    Raises with a clear message when fewer devices exist — the moral
    equivalent of the reference's world-size checks
    (`server_model_data_parallel.py:184`).
    """
    devs = jax.devices(backend) if backend else jax.devices()
    if n is None:
        return list(devs)
    if len(devs) < n:
        raise ValueError(
            f"requested {n} devices but only {len(devs)} available "
            f"({[d.platform for d in devs[:4]]}...). For CPU-simulated meshes set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N before importing jax."
        )
    return list(devs[:n])


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh description: ordered ``{axis_name: size}``.

    ``size == -1`` for at most one axis means "all remaining devices".
    """

    axes: Mapping[str, int]

    def resolve(self, n_devices: int) -> dict[str, int]:
        axes = dict(self.axes)
        wild = [k for k, v in axes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        fixed = math.prod(v for v in axes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by {fixed}")
            axes[wild[0]] = n_devices // fixed
        total = math.prod(axes.values())
        if total != n_devices:
            raise ValueError(
                f"mesh {axes} needs {total} devices, have {n_devices}"
            )
        return axes

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        devices = list(devices) if devices is not None else get_devices()
        axes = self.resolve(len(devices))
        grid = np.asarray(devices).reshape(tuple(axes.values()))
        return Mesh(grid, tuple(axes.keys()))


def make_mesh(
    axes: Mapping[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a mesh from ``{axis: size}`` (one axis may be -1 = remaining)."""
    return MeshSpec(axes).build(devices)


def data_mesh(n: int | None = None) -> Mesh:
    """1-D data-parallel mesh over all (or first ``n``) devices."""
    return make_mesh({"data": -1}, get_devices(n))


def data_model_mesh(model: int, n: int | None = None) -> Mesh:
    """2-D data × model mesh (hybrid DP × parameter sharding).

    ``model`` is the fastest-varying axis so model-axis collectives stay on
    adjacent chips.
    """
    return make_mesh({"data": -1, "model": model}, get_devices(n))


def pipeline_mesh(stages: int, n: int | None = None) -> Mesh:
    """2-D data × stage mesh for (optionally data-parallel) pipelining."""
    return make_mesh({"data": -1, "stage": stages}, get_devices(n))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for a [batch, ...] array split along the data axis."""
    return NamedSharding(mesh, P(axis))


def local_batch_size(global_batch: int, mesh: Mesh, axis: str = "data") -> int:
    n = mesh.shape[axis]
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by {axis}={n}")
    return global_batch // n
