"""Router-side fleet-global prefix directory.

Each replica publishes a prefix summary at ``{ns}/prefix/{rid}``
(checksummed frame, kind="prefix"): the opaque client-stamped affinity
hashes it recently admitted (PR 14) plus — since the tiered-KV
subsystem — the rolling CHAIN hashes resident in its HBM prefix cache
and host spill tier, its KV block size, its weights version, and a
wall-clock publish stamp.  The :class:`PrefixDirectory` is the router's
read side: one refresh per poll, shared by every dispatch decision.

Two lookups come out of it:

* :meth:`affinity` — the opaque-hash map ``_pick`` uses to sort
  prefix-hit candidates first (unchanged semantics from PR 14, now with
  a staleness bound).
* :meth:`best_owner` — content-based coverage: given a request's raw
  prompt, which replica's resident chain (HBM ∪ tier) covers the
  longest leading block run?  This is what triggers a pull-mode KV
  export when the covering replica is not dispatchable — the request
  lands elsewhere WITH the owner's pages instead of re-prefilling.

Staleness: a summary is advisory, so a dead-but-registered replica must
not keep attracting affinity traffic through its last publish.  Any
summary older than ``TPUDIST_PREFIX_SUMMARY_TTL_S`` (wall-clock ``at``
stamp; legacy summaries without a stamp are treated as fresh for
compatibility) is skipped and counted at ``router/prefix_stale_skips``
— the same publish-age discipline the health monitor applies to
metrics.
"""

from __future__ import annotations

import os
import time
from typing import Sequence

from tpudist import obs
from tpudist.runtime import wire
from tpudist.utils.logging import get_logger

log = get_logger(__name__)

__all__ = ["PrefixDirectory", "summary_ttl_from_env",
           "DEFAULT_SUMMARY_TTL_S"]

DEFAULT_SUMMARY_TTL_S = 15.0


def summary_ttl_from_env(default: float = DEFAULT_SUMMARY_TTL_S) -> float:
    """Prefix-summary staleness bound from
    ``TPUDIST_PREFIX_SUMMARY_TTL_S``; non-positive or unparsable values
    fall back to the default (the bound is a correctness-adjacent
    safety net, never disabled)."""
    raw = os.environ.get("TPUDIST_PREFIX_SUMMARY_TTL_S")
    if raw is None:
        return float(default)
    try:
        ttl = float(raw)
    except ValueError:
        return float(default)
    return ttl if ttl > 0 else float(default)


class PrefixDirectory:
    """One router's view of every replica's published prefix summary.

    :meth:`refresh` re-reads the summaries once per poll; the lookup
    methods then run against the in-memory snapshot.  Everything here
    is advisory — corrupt, missing, or stale summaries degrade to
    no-affinity / no-pull, never to a routing error.
    """

    def __init__(self, client, *, namespace: str,
                 ttl_s: float | None = None, wall=time.time) -> None:
        self.client = client
        self.ns = namespace
        self.ttl_s = (summary_ttl_from_env() if ttl_s is None
                      else float(ttl_s))
        self._wall = wall
        # rid -> decoded summary: {"hashes": set, "chains": set,
        #   "block_size": int|None, "version": int|None, "at": float}
        self._summaries: dict[str, dict] = {}
        # per-refresh chain memo: (prompt bytes, block_size) -> chain —
        # best_owner runs once per unassigned entry per poll, and every
        # entry with the same prompt/block-size shares one hash walk
        self._chain_memo: dict[tuple[bytes, int], list[int]] = {}
        self._obs_stale = obs.counter("router/prefix_stale_skips",
                                      unit="summaries")

    # -- read side ---------------------------------------------------------

    def refresh(self, rids: Sequence[str]) -> None:
        """Re-read ``{ns}/prefix/{rid}`` for every rid, applying the
        TTL.  A coord error mid-walk keeps whatever was read so far —
        the steer is advisory and a partial view still routes."""
        self._summaries = {}
        self._chain_memo = {}
        now = self._wall()
        for rid in rids:
            try:
                raw = self.client.get(f"{self.ns}/prefix/{rid}")
            except ConnectionError:
                break
            if raw is None:
                continue
            try:
                doc = wire.decode_record(raw, expect="prefix",
                                         namespace=self.ns, key=rid,
                                         replica=rid)
                at = doc.get("at")
                if at is not None and now - float(at) > self.ttl_s:
                    # dead-but-registered (or wedged) replica: its last
                    # publish must not keep attracting affinity traffic
                    self._obs_stale.inc()
                    continue
                bs = doc.get("block_size")
                self._summaries[rid] = {
                    "hashes": {int(h) for h in doc.get("hashes", [])},
                    "chains": {int(h) for h in doc.get("chains", [])},
                    "block_size": None if bs is None else int(bs),
                    "version": doc.get("version"),
                    "at": float(at) if at is not None else now,
                }
            except (wire.WireError, ValueError, TypeError):
                continue

    # -- lookups -----------------------------------------------------------

    def affinity(self, candidates: Sequence[str]) -> dict[str, set[int]]:
        """The opaque client-stamped affinity map ``Router._pick``
        consumes: ``{rid: {prefix_hash, ...}}`` for the fresh summaries
        among ``candidates``."""
        return {rid: self._summaries[rid]["hashes"]
                for rid in candidates if rid in self._summaries}

    def _chain_for(self, prompt, block_size: int) -> list[int]:
        from tpudist.models.kv_pages import chain_hashes

        import numpy as np

        p = np.asarray(prompt, np.int32)
        key = (p.tobytes(), int(block_size))
        got = self._chain_memo.get(key)
        if got is None:
            got = chain_hashes(p.tolist(), int(block_size))
            self._chain_memo[key] = got
        return got

    def coverage(self, rid: str, prompt) -> int:
        """Leading full blocks of ``prompt`` resident on ``rid`` (HBM
        prefix cache or host tier), per its own advertised block size.
        0 for unknown replicas or summaries without chain data."""
        summ = self._summaries.get(rid)
        if summ is None or not summ["chains"] or not summ["block_size"]:
            return 0
        chains = summ["chains"]
        n = 0
        for h in self._chain_for(prompt, summ["block_size"]):
            if h not in chains:
                break
            n += 1
        return n

    def best_owner(self, prompt, *, live: set[str] | None = None,
                   exclude: Sequence[str] = ()) -> tuple[str | None, int]:
        """``(rid, blocks)`` of the fresh-summaried replica whose
        resident chains cover the longest leading run of ``prompt`` —
        the pull-mode export source.  ``live`` restricts to replicas
        that can still answer a pull request; ties break on rid for
        determinism."""
        best, best_cov = None, 0
        skip = set(exclude)
        for rid in sorted(self._summaries):
            if rid in skip or (live is not None and rid not in live):
                continue
            cov = self.coverage(rid, prompt)
            if cov > best_cov:
                best, best_cov = rid, cov
        return best, best_cov

    def __len__(self) -> int:
        return len(self._summaries)
