"""Quarantine: the router's third replica state besides live and dead.

The fleet's crash-fault machinery (PRs 6-12) handles replicas that
VANISH — lease lapses, inbox drained, work redispatched.  The byzantine
complement is a replica that stays alive and WRONG: flaky HBM flipping
payload bits, NaN-poisoned decode state, a link corrupting frames.
Killing it on the first bad payload is the wrong reflex (one flipped
bit on a healthy node would halve a two-replica fleet); trusting it is
worse (it keeps serving garbage).  Quarantine is the middle state:

* **strikes** — every integrity signal the fleet can attribute to a
  replica (a checksum mismatch on its committed payload, a
  ``corrupt_segment`` / ``wire_error`` verdict it reported, a failed
  golden probe) lands here as a STRIKE.  Strikes age out of a sliding
  window; ``strike_threshold`` strikes inside ``strike_window_s``
  quarantines the replica.
* **quarantined** — the replica is excluded from dispatch (the router
  drops it from candidates and redispatches its outstanding work) and
  marked in the store (``{ns}/quarantined/{rid}``) so the autoscaler
  counts its capacity as missing and backfills — but it is NOT
  stopped: it keeps heartbeating and polling its inbox, which is
  exactly what lets it be probed.
* **golden probes** — the quarantine loop periodically sends the
  replica a fixed probe request (``probe-{rid}-{seq}`` key, outside
  the router's request sequence space) whose greedy output is known
  exactly — the same warmed-but-wrong check the blue-green canary
  runs at rollout time, running in steady state.  ``reinstate_after``
  CONSECUTIVE exact passes lift the quarantine; ``retire_after_fails``
  total failures (mismatch, undecodable completion, or probe timeout)
  retire the replica with a terminal verdict — a targeted stop, after
  which the normal death sweep cleans up.

Every decision is driven by an injectable monotonic clock, so the
state machine is unit-testable without sleeping and runs unchanged on
the offline simulator's virtual clock.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from tpudist import obs
from tpudist.runtime import wire
from tpudist.utils.logging import get_logger

log = get_logger(__name__)

__all__ = ["QuarantineConfig", "GoldenProbe", "QuarantineManager"]


@dataclasses.dataclass(frozen=True)
class QuarantineConfig:
    """Strike/probe/reinstate policy.

    Defaults are tuned for the tiny-fleet benches and tests; a real
    deployment would stretch the windows by the same factor as its
    heartbeat TTLs.
    """
    strike_threshold: int = 3      # strikes in window -> quarantine
    strike_window_s: float = 30.0  # sliding strike window
    probe_interval_s: float = 1.0  # gap between golden probes
    probe_timeout_s: float = 15.0  # unanswered probe counts as a fail
    reinstate_after: int = 3       # consecutive passes -> reinstate
    retire_after_fails: int = 5    # total fails -> terminal verdict

    def __post_init__(self) -> None:
        for name in ("strike_threshold", "reinstate_after",
                     "retire_after_fails"):
            if int(getattr(self, name)) < 1:
                raise ValueError(
                    f"{name} must be >= 1, got {getattr(self, name)}")
        for name in ("strike_window_s", "probe_interval_s",
                     "probe_timeout_s"):
            if float(getattr(self, name)) <= 0:
                raise ValueError(
                    f"{name} must be > 0, got {getattr(self, name)}")


@dataclasses.dataclass(frozen=True)
class GoldenProbe:
    """A fixed probe request and its known-exact greedy output,
    computed against a reference loop on the fleet's weights (the same
    way ``roll_structural`` callers compute ``expect_tokens``)."""
    prompt: tuple
    expect: tuple
    max_new_tokens: int = 0   # 0: default to len(expect)

    def budget(self) -> int:
        return int(self.max_new_tokens) or len(self.expect)


class QuarantineManager:
    """Strike ledger + probe driver, owned by a router.

    The router calls :meth:`strike` from its integrity-failure paths
    and :meth:`tick` once per poll; everything else (marker keys,
    probe traffic, reinstatement, retirement) happens here.  All coord
    I/O is best-effort: a brownout skips a tick, never wedges it.
    """

    def __init__(self, client, *, namespace: str,
                 golden: GoldenProbe | None = None,
                 config: QuarantineConfig | None = None,
                 clock=time.monotonic) -> None:
        self.client = client
        self.ns = namespace
        self.golden = golden
        self.cfg = config or QuarantineConfig()
        self._clock = clock
        self._strikes: dict[str, list[tuple[float, str]]] = {}
        self._q: dict[str, dict] = {}   # rid -> quarantine state
        self._probe_seq = 0
        self._obs_strikes = obs.counter("quarantine/strikes",
                                        unit="strikes")
        self._obs_quarantines = obs.counter("router/quarantines",
                                            unit="replicas")
        self._obs_reinstated = obs.counter("router/reinstated",
                                           unit="replicas")
        self._obs_retired = obs.counter("router/retired",
                                        unit="replicas")
        self._obs_probe_sent = obs.counter("probe/sent", unit="probes")
        self._obs_probe_pass = obs.counter("probe/pass", unit="probes")
        self._obs_probe_fail = obs.counter("probe/fail", unit="probes")
        self._obs_quarantined = obs.gauge("router/quarantined",
                                          unit="replicas")

    # -- inspection --------------------------------------------------------

    def quarantined(self) -> set[str]:
        """Replica ids currently excluded from dispatch (retired ones
        stay here until the death sweep reaps them)."""
        return set(self._q)

    def state(self, rid: str) -> dict | None:
        """A copy of one replica's quarantine state, or ``None``."""
        st = self._q.get(rid)
        return dict(st) if st is not None else None

    def strikes(self, rid: str) -> int:
        """Strikes currently inside the sliding window."""
        return len(self._window(rid, self._clock()))

    # -- strikes -----------------------------------------------------------

    def _window(self, rid: str, now: float) -> list[tuple[float, str]]:
        w = [(t, kind) for t, kind in self._strikes.get(rid, ())
             if now - t <= self.cfg.strike_window_s]
        self._strikes[rid] = w
        return w

    def strike(self, rid: str, kind: str) -> bool:
        """Record one integrity strike against ``rid``; returns True
        when this strike tips the replica into quarantine."""
        if not rid:
            return False
        now = self._clock()
        self._obs_strikes.inc()
        w = self._window(rid, now)
        w.append((now, str(kind)))
        if rid in self._q:
            return False
        if len(w) < self.cfg.strike_threshold:
            log.warning("quarantine: integrity strike %d/%d against "
                        "replica %s (%s)", len(w),
                        self.cfg.strike_threshold, rid, kind)
            return False
        self._enter(rid, now, [k for _, k in w])
        return True

    def _enter(self, rid: str, now: float, kinds: list[str]) -> None:
        self._q[rid] = {"since": now, "passes": 0, "fails": 0,
                        "probe": None, "last_probe_at": float("-inf"),
                        "retired": False, "kinds": list(kinds)}
        self._obs_quarantines.inc()
        self._obs_quarantined.set(len(self._q))
        try:
            self.client.set(
                f"{self.ns}/quarantined/{rid}",
                wire.encode_record("heartbeat", {
                    "replica": rid, "kinds": kinds}))
        except ConnectionError:
            pass   # marker retried implicitly: tick() re-asserts it
        log.warning("quarantine: replica %s quarantined after strikes "
                    "%s — drained from dispatch, probing for "
                    "reinstatement", rid, kinds)

    # -- the probe loop ----------------------------------------------------

    def tick(self, live: set[str] | None = None) -> None:
        """Drive every quarantined replica's probe cycle one step:
        consume an answered probe (exact-match -> pass, anything else
        -> fail), time out an unanswered one, send the next when the
        interval has elapsed.  Without a golden probe configured the
        replica simply stays quarantined — exclusion is still the safe
        state, there is just no evidence path back in."""
        if not self._q:
            return
        now = self._clock()
        for rid in list(self._q):
            st = self._q[rid]
            if st["retired"]:
                continue
            if st["probe"] is not None:
                self._check_probe(rid, st, now)
            if (st["probe"] is None and not st["retired"]
                    and self.golden is not None
                    and (live is None or rid in live)
                    and now - st["last_probe_at"]
                    >= self.cfg.probe_interval_s):
                self._send_probe(rid, st, now)

    def _send_probe(self, rid: str, st: dict, now: float) -> None:
        key = f"probe-{rid}-{self._probe_seq:06d}"
        self._probe_seq += 1
        doc = {"key": key,
               "prompt": [int(t) for t in self.golden.prompt],
               "max_new_tokens": self.golden.budget(),
               "deadline_s": None, "priority": 0}
        try:
            self.client.set(f"{self.ns}/inbox/{rid}/{key}",
                            wire.encode_record("request", doc))
        except ConnectionError:
            return
        st["probe"] = {"key": key, "at": now}
        st["last_probe_at"] = now
        self._obs_probe_sent.inc()

    def _check_probe(self, rid: str, st: dict, now: float) -> None:
        probe = st["probe"]
        done_key = f"{self.ns}/done/{probe['key']}"
        try:
            raw = self.client.get(done_key)
        except ConnectionError:
            return
        if raw is None:
            if now - probe["at"] > self.cfg.probe_timeout_s:
                st["probe"] = None
                self._fail(rid, st, "probe timed out")
            return
        try:
            self.client.delete(done_key)
        except ConnectionError:
            pass
        st["probe"] = None
        try:
            doc = wire.decode_record(raw, expect="completion",
                                     namespace=self.ns,
                                     key=probe["key"], replica=rid)
        except wire.WireError as err:
            # a probe answer the replica corrupted IN TRANSIT is the
            # strongest possible fail signal
            self._fail(rid, st, f"undecodable probe answer "
                                f"({err.reason})")
            return
        got = np.asarray(doc.get("tokens", ()), np.int32)
        expect = np.asarray(self.golden.expect, np.int32)
        if (doc.get("reason") in ("stop", "length")
                and np.array_equal(got, expect)):
            self._pass(rid, st)
        else:
            self._fail(
                rid, st,
                f"output mismatch (got {got.tolist()}, expected "
                f"{expect.tolist()}, reason {doc.get('reason')!r})")

    def _pass(self, rid: str, st: dict) -> None:
        self._obs_probe_pass.inc()
        st["passes"] += 1
        log.info("quarantine: replica %s passed golden probe %d/%d",
                 rid, st["passes"], self.cfg.reinstate_after)
        if st["passes"] >= self.cfg.reinstate_after:
            self._reinstate(rid)

    def _fail(self, rid: str, st: dict, why: str) -> None:
        self._obs_probe_fail.inc()
        st["fails"] += 1
        st["passes"] = 0   # reinstatement needs CONSECUTIVE passes
        log.warning("quarantine: replica %s failed golden probe "
                    "(%d total): %s", rid, st["fails"], why)
        if st["fails"] >= self.cfg.retire_after_fails:
            self._retire(rid, st)

    def _reinstate(self, rid: str) -> None:
        del self._q[rid]
        self._strikes.pop(rid, None)
        self._obs_reinstated.inc()
        self._obs_quarantined.set(len(self._q))
        try:
            self.client.delete(f"{self.ns}/quarantined/{rid}")
        except ConnectionError:
            pass
        log.info("quarantine: replica %s reinstated after %d clean "
                 "probes", rid, self.cfg.reinstate_after)

    def _retire(self, rid: str, st: dict) -> None:
        st["retired"] = True
        self._obs_retired.inc()
        try:
            self.client.set(f"{self.ns}/stop/{rid}", b"1")
        except ConnectionError:
            pass
        log.error("quarantine: replica %s RETIRED after %d failed "
                  "probes — stopping it; the death sweep reaps the "
                  "residue and the autoscaler backfills", rid,
                  st["fails"])

    def drop(self, rid: str) -> None:
        """Forget a replica (called by the router's death sweep): its
        marker key is gone with the rest of its residue, and a future
        replica reusing the id starts with a clean ledger."""
        self._q.pop(rid, None)
        self._strikes.pop(rid, None)
        self._obs_quarantined.set(len(self._q))
