"""Fault-tolerant serve fleet: a router tier over N ``ServeLoop`` replicas.

ROADMAP item 4's millions-of-users shape: capacity and availability come
from REPLICAS behind a router, not from one bigger loop.  Everything
rides the coordination planes that already exist — no new transport:

* **Liveness** — each replica holds a TTL heartbeat lease
  (``{ns}:{rid}`` via :class:`~tpudist.runtime.coord.ElasticMonitor`);
  the router's death signal is the lease expiring, exactly the signal
  elastic training uses.
* **Load** — each replica publishes its metric snapshot
  (:class:`~tpudist.obs.aggregate.MetricsPublisher` under
  ``{ns}/metrics``); the router admits least-loaded from the published
  ``serve/kv_blocks_free`` / ``serve/queue_depth`` gauges and the
  ``serve/queue_wait_s`` histogram, cross-checked by a
  :class:`~tpudist.obs.health.HealthMonitor` over the same snapshots
  (a replica whose publisher went quiet is excluded before its
  heartbeat ever lapses).
* **Requests** — the router writes each admitted request to the chosen
  replica's inbox (``{ns}/inbox/{rid}/{key}``); the replica's
  :class:`ReplicaWorker` feeds them to ``ServeLoop.run``'s service mode
  and writes each completion to ``{ns}/done/{key}``.

Failure model (the robustness core):

* **Death detection** — a replica absent from ``live()`` (TTL lapsed,
  e.g. SIGKILL or an injected heartbeat drop) or classified ``lost`` by
  the health monitor is dead to the router.
* **Drain + redispatch** — the dead replica's inbox is swept and every
  request assigned to it (picked up or not) is re-enqueued and
  dispatched to a survivor.  Redispatched requests restart from the
  prompt; greedy decoding over identical replica weights makes the
  redispatched output token-identical to an uninterrupted run.
* **Exactly-once completion** — the router consumes ``done`` keys
  (get + delete) keyed by its own request id and returns the FIRST
  completion per request; a false-positive death (replica alive but
  presumed dead, e.g. dropped heartbeats) can produce a duplicate done
  write, but under greedy determinism the duplicate is byte-identical
  and is simply deleted.  Every admitted request returns exactly one
  :class:`~tpudist.models.serving.Completion`.
* **Bounded time** — :meth:`Router.run` raises :class:`TimeoutError`
  at its ``timeout_s`` bound instead of hanging when no capacity
  remains (every replica dead).

Replica-side load shedding composes with routing: a replica that sheds
(``reason="rejected"``, ``serve/rejected`` counter) gets its requests
re-routed and is put on a short admission backoff instead of being
hammered while saturated.

Elastic membership (ROADMAP item 4's remainder — this is what makes the
fleet elastic UPWARD, not just shrink-on-death):

* **Live join** — the router discovers registrations on every poll
  (membership is never frozen at construction); a replica started
  against a running fleet (:func:`scale_fleet`) registers, restores the
  fleet's weight snapshot (``--snapshot-dir`` →
  :class:`~tpudist.elastic.checkpoint.Checkpointer`), heartbeats, and
  takes traffic.  First-poll members are the baseline; later
  appearances tick ``router/joins``.
* **Rolling weight hot-swap** — :func:`roll_weights` persists the new
  weights to the snapshot dir (durability FIRST), then bumps
  ``{ns}/weights/version``.  Each replica notices the bump, takes a
  ticket (``add({ns}/weights/ticket/{v}, 1)``), and swaps only when the
  done counter (``{ns}/weights/done/{v}``) shows every earlier ticket
  finished — one replica at a time, so fleet capacity never drops by
  more than one replica.  The swap itself is
  :meth:`~tpudist.models.serving.ServeLoop.request_swap`: drain
  in-flight decodes on the old weights, rebind, resume — zero lost or
  version-straddling requests.  While swapping, the replica publishes
  ``serve/swapping=1`` and the router steers admissions around it.  A
  ticket-holder that DIES mid-chain would stall it forever; after
  ``swap_turn_timeout_s`` a waiting replica proceeds anyway (liveness
  over strict seriality — the race is only two replicas briefly
  swapping at once).
* **Router-side SLO admission** — a deadline-bearing request is SHED at
  the router (``reason="shed"``, ``router/slo_shed``) when even the
  best candidate's published ``serve/queue_wait_s`` percentile
  (``slo_quantile``, default p99) predicts a miss — before the request
  ever costs any replica a prefill.

Fleet control plane (ISSUE 9 — the policy layer over those mechanisms):

* **Join grace** — the death sweep covers registered-but-not-yet-live
  rids (so a joiner that died during warmup cannot pin its
  registration), but a NEVER-live registration younger than
  ``join_grace_s`` is forgiven: a slow-warming joiner (minutes of
  compile before its first heartbeat) must not be swept as dead.  Once
  a replica has ever heartbeated, a lapsed lease is death NOW — grace
  never stretches kill detection.
* **Graceful drain** — ``{ns}/draining/{rid}`` (:func:`request_drain`)
  steers admissions away immediately; :func:`drain_replicas` stops the
  replica only once its inbox is empty (the worker's close path
  finishes queued + in-flight work and commits every completion), then
  sweeps the coordination residue.  A draining departure ticks
  ``router/drains``, not the ``router/replica_deaths`` counter that
  pages an operator.
* **Blue-green structural rollout** — :meth:`Router.roll_structural`
  spins up a tagged green pool (``--pool``), warms it, exact-checks a
  canary request against a reference, then commits by shifting the
  ``{ns}/pool`` pin and draining blue; any warmup/canary failure rolls
  back with blue never touched.  The in-place hot-swap handles weight
  DELTAS; this handles changes a running loop cannot absorb.
* **Overload degradation** — past a replica's soft ``degrade_queue``
  watermark it advertises ``serve/degraded`` and clamps best-effort
  (``Request.priority <= 0``) budgets to ``degrade_max_new``; past the
  hard ``max_queue`` bound it sheds lowest-priority-newest-first.  The
  router mirrors the fleet's degraded state (``router/degraded``) and
  clamps best-effort budgets at dispatch (``degrade_max_new``).
* **Collision-safe scale-up** — replica indices come from an atomic
  add-chain (``{ns}/replica_index`` via :func:`alloc_replica_indices`),
  so concurrent :func:`scale_fleet` callers (autoscaler + operator)
  can never mint the same rid.

The autoscaler (:mod:`tpudist.runtime.autoscaler`) closes the loop:
it watches the fleet-merged windowed ``serve/queue_wait_s`` percentile
and drives :func:`scale_fleet` / the drain protocol itself.

The fault-injection harness (:mod:`tpudist.runtime.faults`,
``TPUDIST_FAULT_*``) exercises all of this deterministically: coord-op
errors/delays hit the retry paths, ``KILL_AFTER_SEGMENTS`` SIGKILLs a
replica mid-decode, ``HEARTBEAT_STOP_AFTER_S`` fakes death without
stopping the worker, ``PUBLISH_DROP`` starves the obs plane so the
health monitor's ``stale`` verdict steers routing without a death,
``HEARTBEAT_DELAY_S`` recreates the slow-warming joiner,
``KILL_AT_WARMUP`` SIGKILLs a joiner between registration and its
first heartbeat, and ``CANARY_CORRUPT`` forces the green pool to serve
wrong canary output so the rollback path runs for real.

Data-plane integrity (the byzantine-fault complement to the crash
machinery above; see docs/DESIGN.md "Data-plane integrity"):

* **Checksummed wire** — requests, completions, and journal records
  are framed by :mod:`tpudist.runtime.wire` (crc32c + schema tag);
  every decode site verifies before trusting.  A mismatch raises a
  typed :class:`~tpudist.runtime.wire.WireError` carrying
  namespace/key/replica, which the router COUNTS
  (``integrity/checksum_mismatch``), attributes as a strike against
  the offending replica, and answers by deleting the corrupt key and
  redispatching the request — corruption is never delivered and never
  crashes the poll loop.  Unframed legacy payloads still decode (the
  simulator's fakes and hand-planted test keys ride that path).
* **In-band verdicts** — a replica that catches corruption itself
  (NaN/inf logits freezing a lane into ``reason="corrupt_segment"``,
  or an undecodable inbox payload surfacing as
  ``reason="wire_error"``) commits the verdict instead of output; the
  router re-routes the request like a rejection and records a strike.
* **Quarantine** — strikes accumulate in
  :class:`~tpudist.runtime.quarantine.QuarantineManager`; past the
  threshold the replica is drained from dispatch (not killed), marked
  ``{ns}/quarantined/{rid}`` (the autoscaler backfills the capacity),
  and re-probed with golden queries — fixed prompt, known-exact greedy
  tokens, the blue-green canary check running in steady state — until
  it is reinstated by consecutive clean probes or retired.  The fault
  knobs ``FLIP_WIRE_BITS``, ``NAN_AFTER_TOKENS``, and ``PROBE_FAIL``
  drive all three paths deterministically.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Any, Sequence

import numpy as np

from tpudist import obs
from tpudist.obs.aggregate import collect, MetricsPublisher
from tpudist.obs.events import EventPublisher, TraceContext
from tpudist.obs.health import HealthMonitor
from tpudist.obs.registry import hist_quantile
from tpudist.runtime import faults, wire
from tpudist.runtime.coord import CoordClient, ElasticMonitor
from tpudist.runtime.prefix_directory import (PrefixDirectory,
                                              summary_ttl_from_env)
from tpudist.runtime.quarantine import (GoldenProbe, QuarantineConfig,
                                        QuarantineManager)
from tpudist.utils.logging import get_logger

log = get_logger(__name__)

__all__ = ["Router", "ReplicaWorker", "build_tiny_lm",
           "launch_local_fleet", "scale_fleet", "stop_fleet",
           "exit_reports", "wait_live", "roll_weights", "wait_swapped",
           "alloc_replica_indices", "request_drain", "drain_replicas",
           "JOURNAL_SCHEMA", "GoldenProbe", "QuarantineConfig",
           "QuarantineManager"]

DEFAULT_NAMESPACE = "fleet"

# version tag every {ns}/journal/* record carries (see docs/DESIGN.md
# "Control-plane recovery" for the schema and commit-point rules)
JOURNAL_SCHEMA = "tpudist.journal/1"


# -- wire format (checksummed JSON frames over the KV store; see
# tpudist.runtime.wire for the crc32c framing and the legacy
# unframed-JSON fallback every decoder keeps) ------------------------------

def _request_doc(key: str, req, handoff_ref: str | None = None,
                 prefix_ref: str | None = None) -> dict:
    doc = {
        "key": key,
        "prompt": np.asarray(req.prompt).astype(int).tolist(),
        "max_new_tokens": int(req.max_new_tokens),
        "deadline_s": req.deadline_s,
        "priority": int(getattr(req, "priority", 0)),
    }
    # disaggregated decode-stage dispatch: the KV-migration payload's
    # transport ref rides the wire (never the payload itself — the
    # request stays small); the replica fetches and adopts, or
    # re-prefills from the prompt above when the fetch misses
    if handoff_ref is not None:
        doc["handoff_ref"] = str(handoff_ref)
    # pull-mode global prefix cache: the ref of a peer-exported prefix
    # payload — the replica fetches and installs the shared pages
    # BEFORE admission, so the prefill covers only the suffix (a miss
    # installs nothing and the full prefill runs: exact either way)
    if prefix_ref is not None:
        doc["prefix_ref"] = str(prefix_ref)
    # distributed tracing: the trace context rides the wire so the
    # replica's lifecycle events join the router's under one trace id
    # (and SURVIVE a redispatch — the router re-sends the same context)
    trace = getattr(req, "trace", None)
    if trace is not None:
        doc["trace"] = trace.to_wire()
    # prefix affinity: the OPAQUE client-stamped hash rides the wire so
    # the replica can advertise it back in its prefix summary — router
    # and replica never have to agree on block size or hash chaining
    phash = getattr(req, "prefix_hash", None)
    if phash is not None:
        doc["prefix_hash"] = int(phash)
    return doc


def _encode_request(key: str, req,
                    handoff_ref: str | None = None,
                    prefix_ref: str | None = None) -> bytes:
    return wire.encode_record(
        "request", _request_doc(key, req, handoff_ref=handoff_ref,
                                prefix_ref=prefix_ref))


def _decode_request(raw: bytes, *, namespace: str = "", key: str = "",
                    replica: str = ""):
    """Verify + decode one inbox payload into a ``Request``.  Raises
    :class:`~tpudist.runtime.wire.WireError` on ANY failure — checksum,
    truncation, bad JSON, or a structurally valid document missing the
    request fields — so one except clause covers the whole corrupt
    surface at each call site."""
    from tpudist.models.serving import Request

    d = wire.decode_record(raw, expect="request", namespace=namespace,
                           key=key, replica=replica)
    try:
        phash = d.get("prefix_hash")
        ref = d.get("handoff_ref")
        pref = d.get("prefix_ref")
        return Request(prompt=np.asarray(d["prompt"], np.int32),
                       max_new_tokens=int(d["max_new_tokens"]),
                       rid=d["key"], deadline_s=d.get("deadline_s"),
                       priority=int(d.get("priority", 0)),
                       trace=TraceContext.from_wire(d.get("trace")),
                       prefix_hash=None if phash is None else int(phash),
                       prefix_ref=None if pref is None else str(pref),
                       # a ref-only stub: the worker resolves it into
                       # the real payload (or None) before admission
                       kv_handoff=(None if ref is None
                                   else {"handoff_ref": str(ref)}))
    except (KeyError, ValueError, TypeError):
        raise wire.WireError("schema", kind="request",
                             namespace=namespace, key=key,
                             replica=replica) from None


def _encode_completion(replica_id: str, comp,
                       handoff_ref: str | None = None,
                       adopt_fallback: bool = False) -> bytes:
    doc = {
        "key": comp.rid,
        "tokens": np.asarray(comp.tokens).astype(int).tolist(),
        "reason": comp.reason,
        "replica": replica_id,
    }
    # reason="handoff"/"migrate" commits carry the migration payload's
    # transport ref, NOT the payload (that crossed separately, before
    # this commit): the router journals the ref and re-sends it on the
    # decode-stage dispatch
    if handoff_ref is not None:
        doc["handoff_ref"] = str(handoff_ref)
    if adopt_fallback:
        # this request was dispatched with a payload ref whose fetch
        # missed — the replica re-prefilled (exact); the router counts
        # it against router/migration_fallbacks when the entry migrated
        doc["adopt_fallback"] = True
    return wire.encode_record("completion", doc)


# -- the replica side ------------------------------------------------------

class ReplicaWorker:
    """One serve replica: a ``ServeLoop`` in service mode, wired to the
    fleet's coordination planes.

    Lifecycle: :meth:`serve` registers the replica
    (``{ns}/replica/{rid}``), starts the TTL heartbeat and the metrics
    publisher, then blocks in ``loop.run(source=..., sink=...)`` — the
    source polls the inbox (FIFO by key) and watches the stop keys
    (``{ns}/stop`` fleet-wide, ``{ns}/stop/{rid}`` targeted); the sink
    commits each completion to ``{ns}/done/{key}``.  On a clean exit an
    exit report (``{ns}/exit/{rid}``: served count, pool-drained flag)
    lets cross-process tests assert the no-orphaned-blocks invariant.

    Elastic pieces (see the module docstring's protocol sketch):

    * ``snapshot_dir`` — the fleet's shared weight snapshot.  At
      construction the worker restores the latest committed checkpoint
      (a JOINER starts on the fleet's current weights, keeping greedy
      output exact-match); at a version bump it is what
      ``restore_latest`` re-reads for the hot-swap.
    * the source poll watches ``{ns}/weights/version`` and drives the
      rolling one-at-a-time swap chain (ticket + done counter, turn
      timeout for dead ticket-holders), gating the actual rebind
      through ``loop.request_swap`` so in-flight decodes finish on the
      weights that admitted them.
    """

    def __init__(self, loop, client: CoordClient, replica_id: str, *,
                 rank: int = 0, namespace: str = DEFAULT_NAMESPACE,
                 ttl_s: float = 2.0, publish_interval_s: float = 0.25,
                 idle_wait_s: float = 0.01,
                 snapshot_dir: str | os.PathLike | None = None,
                 swap_turn_timeout_s: float = 10.0,
                 pool: str = "default",
                 kv_transport=None) -> None:
        from tpudist.runtime.disagg import CoordKVTransport

        self.loop = loop
        self.client = client
        self.replica_id = replica_id
        # KV-migration channel for disaggregated handoffs: a prefill
        # replica publishes finished-slot KV here before committing the
        # handoff; a decode replica fetches by the dispatched ref.  The
        # coord store is the baseline; pass an IciKVTransport for the
        # device-to-device fast path (colocated loops share one
        # instance).  Unified ("both") replicas never touch it.
        self.kv_transport = (kv_transport if kv_transport is not None
                             else CoordKVTransport(client,
                                                   namespace=namespace))
        self.rank = int(rank)
        self.ns = namespace
        # blue-green pool tag: the router only dispatches to the ACTIVE
        # pool ({ns}/pool key); a structural rollout spawns replicas
        # under a new tag and shifts the key after the canary passes
        self.pool = str(pool)
        self.ttl_s = float(ttl_s)
        self.idle_wait_s = idle_wait_s
        self.snapshot_dir = snapshot_dir
        self.swap_turn_timeout_s = float(swap_turn_timeout_s)
        self._inbox = f"{namespace}/inbox/{replica_id}/"
        self._served = 0
        # coord-brownout degradation: completions that fail to commit
        # (store unreachable) park here and flush on reconnect — the
        # replica keeps decoding through the outage.  Bounded so a
        # never-ending outage cannot grow memory without limit; at the
        # bound the OLDEST is dropped (the router redispatches it after
        # the outage, and greedy determinism re-produces it).
        self._done_buf: list[tuple[str, bytes]] = []
        self._done_buf_cap = 4096
        # rids whose handoff/migration payload fetch missed (the loop
        # re-prefilled): their terminal commits carry adopt_fallback
        self._fallback_rids: set[str] = set()
        # last published prefix-affinity summary; republished on change
        # OR half-TTL age (the summary carries a wall-clock stamp the
        # router's staleness bound reads, so an unchanged-but-alive
        # summary must keep renewing itself)
        self._prefix_pub: tuple | None = None
        self._prefix_pub_t = 0.0
        self._prefix_ttl_s = summary_ttl_from_env()
        self._weights_version = 0
        self._roll: dict | None = None   # the in-progress swap-chain turn
        self._obs_version = obs.gauge("serve/weights_version",
                                      unit="version")
        self._obs_swapping = obs.gauge("serve/swapping", unit="flag")
        self._hb = ElasticMonitor(client, f"{namespace}:{replica_id}",
                                  ttl_s=ttl_s,
                                  interval_s=max(ttl_s / 4, 0.05))
        self._pub = MetricsPublisher(client, self.rank, obs.registry,
                                     namespace=f"{namespace}/metrics",
                                     interval_s=publish_interval_s)
        # request-event ring publisher: per-replica lifecycle events flow
        # to {ns}/events/{rank}; rank 0 (or the bench) merges them into
        # the fleet-wide timeline.  rid -> TraceContext for requests this
        # replica picked up, so the done-commit event can be recorded
        # without widening the completion wire format.
        self._epub = EventPublisher(client, self.rank, obs.events,
                                    namespace=f"{namespace}/events",
                                    interval_s=publish_interval_s)
        self._traces: dict[str, Any] = {}
        if snapshot_dir is not None:
            got = self._restore_latest()
            if got is not None:
                step, tree, meta = got
                import jax
                import jax.numpy as jnp

                self.loop.params = jax.tree.map(jnp.asarray, tree)
                self._weights_version = int(
                    (meta or {}).get("version", step))
                if hasattr(self.loop, "weights_version"):
                    # the loop's KV version stamp must track the served
                    # weights from the first token: tier entries and
                    # pull payloads minted under this version are only
                    # adoptable by peers on the SAME version
                    self.loop.weights_version = self._weights_version
                log.info("replica %s: restored weights version %d from %s",
                         replica_id, self._weights_version, snapshot_dir)
        self._obs_version.set(self._weights_version)
        self._obs_swapping.set(0)

    def register(self) -> None:
        info = {
            "replica_id": self.replica_id,
            "rank": self.rank,
            "pid": os.getpid(),
            "num_slots": self.loop.B,
            "cache_layout": self.loop.cache_layout,
            "kv_num_blocks": self.loop.kv_num_blocks or None,
            "kv_block_size": self.loop.kv_block_size or None,
            "ttl_s": self.ttl_s,
            "pool": self.pool,
            # two-stage scheduling: the router sends fresh requests to
            # role prefill/both and handoff (decode-stage) requests to
            # role decode/both
            "role": getattr(self.loop, "role", "both"),
        }
        self.client.set(f"{self.ns}/replica/{self.replica_id}",
                        json.dumps(info).encode())

    # -- rolling weight hot-swap ------------------------------------------

    def _restore_latest(self):
        """``(step, tree, meta) | None`` from the fleet snapshot dir."""
        from tpudist.elastic.checkpoint import Checkpointer

        return Checkpointer(self.snapshot_dir,
                            layout="steps").restore_latest(self.loop.params)

    def _restore_params(self):
        """The ``params_fn`` handed to ``request_swap``: the new tree,
        or ``None`` (swap aborts, old weights stay) when the snapshot
        is unreadable — a replica must not die over a failed roll."""
        try:
            got = self._restore_latest()
        except Exception as e:  # noqa: BLE001 - torn write, fs error
            log.warning("replica %s: weight restore failed (%s); "
                        "keeping current weights", self.replica_id, e)
            return None
        return None if got is None else got[1]

    def _finish_roll(self, version: int) -> None:
        """``on_swapped``: the drain-gated rebind just landed (or was
        aborted on a failed restore — either way this replica's TURN is
        over).  Advance the done chain so the next ticket-holder goes,
        and resume advertising for admissions."""
        self._weights_version = int(version)
        self._roll = None
        # the swap flushed the prefix cache AND tier: force a fresh
        # summary publish so the fleet directory unlearns this
        # replica's pre-swap residency immediately
        self._prefix_pub = None
        self._obs_version.set(self._weights_version)
        self._obs_swapping.set(0)
        try:
            self.client.add(f"{self.ns}/weights/done/{version}", 1)
        except ConnectionError:
            # peers fall back to their turn timeout; the chain still
            # completes, just slower
            log.warning("replica %s: could not advance swap done-chain "
                        "for version %d", self.replica_id, version)
        try:
            self._pub.publish()   # the router unlearns `swapping` now
        except Exception:  # noqa: BLE001
            pass
        log.info("replica %s: weights hot-swapped to version %d",
                 self.replica_id, version)

    def _check_weights_roll(self) -> None:
        """One poll of the rolling-upgrade protocol.  Coord errors
        abort the step (retried next poll); `add` is deliberately used
        both to TAKE a ticket (+1) and to READ the done counter (+0).

        One replica at a time: ticket ``t`` swaps when ``done >= t-1``.
        A dead ticket-holder (SIGKILLed mid-chain — the "swap racing a
        death" case) never advances ``done``; after
        ``swap_turn_timeout_s`` of waiting this replica proceeds
        anyway, trading strict seriality for liveness."""
        if self._pending_roll_requested():
            return
        try:
            raw = self.client.get(f"{self.ns}/weights/version")
        except ConnectionError:
            return
        if raw is None:
            return
        try:
            version = int(raw.decode())
        except ValueError:
            return
        if version <= self._weights_version:
            return
        if self._roll is None:
            try:
                ticket = self.client.add(
                    f"{self.ns}/weights/ticket/{version}", 1)
            except ConnectionError:
                return   # may or may not have taken one; see below
            self._roll = {"version": version, "ticket": int(ticket),
                          "since": time.monotonic(), "requested": False}
            log.info("replica %s: weights version %d published; holding "
                     "swap ticket %d", self.replica_id, version, ticket)
        roll = self._roll
        try:
            done = int(self.client.add(
                f"{self.ns}/weights/done/{roll['version']}", 0))
        except ConnectionError:
            return
        waited = time.monotonic() - roll["since"]
        if done < roll["ticket"] - 1 and waited <= self.swap_turn_timeout_s:
            return   # an earlier ticket is still swapping
        if waited > self.swap_turn_timeout_s and done < roll["ticket"] - 1:
            log.warning(
                "replica %s: swap chain for version %d stalled "
                "(done=%d, ticket=%d) after %.1fs; proceeding "
                "(a ticket-holder likely died)", self.replica_id,
                roll["version"], done, roll["ticket"], waited)
        roll["requested"] = True
        # stop advertising for admissions BEFORE draining: the router
        # steers around `swapping` replicas, so requests keep flowing
        # to the rest of the fleet while this one rebinds
        self._obs_swapping.set(1)
        try:
            self._pub.publish()
        except Exception:  # noqa: BLE001
            pass
        self.loop.request_swap(
            self._restore_params, version=roll["version"],
            on_swapped=lambda v=roll["version"]: self._finish_roll(v))

    def _pending_roll_requested(self) -> bool:
        return self._roll is not None and self._roll["requested"]

    def _flush_done_buffer(self) -> None:
        """Re-commit completions parked during a coord brownout, oldest
        first; stop at the first failure (still down)."""
        while self._done_buf:
            key, payload = self._done_buf[0]
            try:
                self.client.set(key, payload)
            except ConnectionError:
                return
            self._done_buf.pop(0)

    def _source(self):
        """One intake poll: ``None`` on a stop key (close and drain),
        else the inbox's requests in key order (the router's dispatch
        order — its keys are zero-padded sequence numbers).  Also the
        tick of the rolling-swap protocol — it rides the same poll
        cadence the loop already guarantees.

        A coord outage mid-poll yields ``[]``, NOT death: in-flight
        decode segments keep running and the poll retries on the
        loop's next tick — replicas ride a brownout out (the buffered
        done commits flush here too)."""
        try:
            self._flush_done_buffer()
            self._publish_prefix()
            self._serve_pulls()
            if getattr(self.loop, "preempt", "degrade") == "migrate":
                # live-migration control plane, checked BEFORE the stop
                # key: a drain sets draining and stop back-to-back once
                # the inbox is empty, and the evacuation armed on this
                # very poll must win that race — the loop keeps flushing
                # migrations after the source closes, so arming here is
                # enough.  Rebalance intents name the requests to
                # evacuate; an intent for a request that already
                # finished is a no-op loop-side (its terminal wins).  A
                # draining flag evacuates EVERYTHING — re-armed every
                # poll so work that arrives after the flag (a racing
                # final dispatch) bounces out too, collapsing drain
                # time to ~one handoff RTT.
                mig_prefix = f"{self.ns}/migrate_req/{self.replica_id}/"
                rids = []
                for key in sorted(self.client.keys(mig_prefix)):
                    self.client.delete(key)
                    rids.append(key[len(mig_prefix):])
                if rids:
                    self.loop.request_migrate(rids)
                if self.client.get(f"{self.ns}/draining/"
                                   f"{self.replica_id}") is not None:
                    self.loop.request_evacuate()
            if (self.client.get(f"{self.ns}/stop") is not None
                    or self.client.get(
                        f"{self.ns}/stop/{self.replica_id}") is not None):
                return None
            if self.snapshot_dir is not None:
                self._check_weights_roll()
            out = []
            for key in sorted(self.client.keys(self._inbox)):
                raw = self.client.get(key)
                self.client.delete(key)
                if raw is None:   # racing a sweep of a presumed death
                    continue
                k = key[len(self._inbox):]
                try:
                    req = _decode_request(raw, namespace=self.ns,
                                          key=k,
                                          replica=self.replica_id)
                except wire.WireError as e:
                    # a corrupt dispatch: silently dropping it would
                    # leave the router waiting until its death sweep or
                    # timeout.  Commit a wire_error VERDICT instead —
                    # the router re-routes the request immediately and
                    # counts the strike against this replica (the
                    # observation point; a replica whose memory or NIC
                    # flips bits accumulates these).
                    log.warning("replica %s: undecodable request %s "
                                "(%s); committing wire_error verdict",
                                self.replica_id, key, e.reason)
                    try:
                        self.client.set(
                            f"{self.ns}/done/{k}",
                            wire.encode_record("completion", {
                                "key": k, "tokens": [],
                                "reason": "wire_error",
                                "replica": self.replica_id,
                                "wire_reason": e.reason}))
                    except ConnectionError:
                        pass
                    continue
                if req.trace is not None:
                    self._traces[str(req.rid)] = req.trace
                req = self._resolve_handoff(req)
                req = self._resolve_prefix_pull(req)
                out.append(req)
        except ConnectionError:
            return []
        return out

    def _serve_pulls(self) -> None:
        """Owner half of the pull-mode global prefix cache: answer
        ``{ns}/pullreq/{rid}/{key}`` requests by exporting the longest
        resident run of the carried prompt's chain (HBM gather or
        host-tier read — the export is a COPY, local residency is
        untouched), publishing it over the KV transport, and committing
        a ``{ns}/pulldone/{key}`` record with the payload ref (or
        ``None`` on a miss — the router reverts the request to an
        ordinary prefill).  Every failure mode degrades to ref=None or
        to the router's pull timeout; a pull can slow a request but
        never lose one."""
        from tpudist.models.kv_pages import chain_hashes

        prefix = f"{self.ns}/pullreq/{self.replica_id}/"
        for key in sorted(self.client.keys(prefix)):
            raw = self.client.get(key)
            self.client.delete(key)
            if raw is None:
                continue
            k = key[len(prefix):]
            ref = None
            try:
                doc = wire.decode_record(raw, expect="pullreq",
                                         namespace=self.ns, key=k,
                                         replica=self.replica_id)
                prompt = [int(t) for t in doc.get("prompt", ())]
                fn = getattr(self.loop, "export_prefix", None)
                bs = getattr(self.loop, "kv_block_size", 0) or 0
                if fn is not None and bs and prompt:
                    payload = fn(chain_hashes(prompt, bs))
                    if payload is not None:
                        payload = dict(payload)
                        payload["key"] = k
                        payload["rid"] = k
                        ref, _ = self.kv_transport.publish(
                            f"pull-{k}", payload)
                        obs.counter("serve/prefix_exports",
                                    unit="payloads").inc()
            except ConnectionError:
                raise   # the outer source poll's brownout handling
            except Exception as e:  # noqa: BLE001 - advisory path
                log.warning("replica %s: prefix export for %s failed "
                            "(%s); answering ref=None", self.replica_id,
                            k, e)
                ref = None
            self.client.set(
                f"{self.ns}/pulldone/{k}",
                wire.encode_record("pulldone", {
                    "key": k, "ref": ref, "owner": self.replica_id}))

    def _resolve_prefix_pull(self, req):
        """Requester half: fetch a dispatched ``prefix_ref`` payload
        and install the peer's pages as local cached-idle prefix blocks
        BEFORE admission, so the admission that follows hits locally
        and prefills only the suffix.  Any miss, corruption, or gate
        failure installs nothing — the ordinary full prefill is the
        byte-identical fallback — and this never raises."""
        ref = getattr(req, "prefix_ref", None)
        if not ref:
            return req
        fn = getattr(self.loop, "install_prefix", None)
        installed = 0
        if fn is not None:
            try:
                payload = self.kv_transport.fetch(ref)
                if payload is not None:
                    installed = int(fn(req.prompt, payload))
            except Exception as e:  # noqa: BLE001 - advisory path
                log.warning("replica %s: prefix install for %s failed "
                            "(%s); falling back to full prefill",
                            self.replica_id, req.rid, e)
        if installed:
            obs.counter("serve/prefix_pull_blocks", unit="blocks").inc(
                installed)
        else:
            obs.counter("serve/prefix_pull_fallbacks", unit="reqs").inc()
            log.info("replica %s: prefix pull for %s yielded no blocks;"
                     " re-prefilling", self.replica_id, req.rid)
        return dataclasses.replace(req, prefix_ref=None)

    def _resolve_handoff(self, req):
        """Swap a decode-stage request's ref stub for the real
        KV-migration payload.  A miss (dropped, corrupt, exporter died
        pre-publish) resolves to ``None`` — the loop re-prefills from
        the carried prompt, so this NEVER raises and never loses the
        request."""
        stub = getattr(req, "kv_handoff", None)
        if not (isinstance(stub, dict) and "handoff_ref" in stub
                and "layers" not in stub):
            return req
        payload = self.kv_transport.fetch(stub["handoff_ref"])
        if payload is None:
            obs.counter("serve/handoff_fallbacks", unit="reqs").inc()
            # remembered until this request's commit: the terminal
            # carries adopt_fallback=True so the router can attribute
            # a migrated request's lost-payload re-prefill
            self._fallback_rids.add(str(req.rid))
            log.warning("replica %s: KV payload %s missing; request %s "
                        "falls back to re-prefill", self.replica_id,
                        stub["handoff_ref"], req.rid)
        return dataclasses.replace(req, kv_handoff=payload)

    def _publish_prefix(self) -> None:
        """Advertise the loop's prefix residency at ``{ns}/prefix/{rid}``
        (checksummed frame, kind="prefix"): the recently admitted opaque
        affinity hashes (PR 14's steer), plus — for the fleet-global
        prefix cache — the CHAIN hashes resident in HBM and the host
        tier, the KV block size, the weights version the bytes were
        computed under, and a wall-clock stamp the router's staleness
        bound reads.  Purely advisory: stale or missing summaries only
        cost cache hits, never correctness, so a publish failure is
        swallowed.  Republished on change or at half-TTL age."""
        fn = getattr(self.loop, "prefix_summary", None)
        summ = tuple(int(h) for h in fn()) if fn is not None else ()
        rfn = getattr(self.loop, "prefix_residency", None)
        res = rfn() if rfn is not None else {"chains": [], "tiered": []}
        now = time.time()
        memo = (summ, tuple(res["chains"]), tuple(res["tiered"]),
                self._weights_version)
        if (memo == self._prefix_pub
                and now - self._prefix_pub_t < self._prefix_ttl_s / 2):
            return
        try:
            self.client.set(
                f"{self.ns}/prefix/{self.replica_id}",
                wire.encode_record("prefix", {
                    "replica": self.replica_id,
                    "hashes": list(summ),
                    "chains": [int(h) for h in res["chains"]],
                    "tiered": [int(h) for h in res["tiered"]],
                    "block_size": getattr(self.loop, "kv_block_size",
                                          None) or None,
                    "version": self._weights_version,
                    "at": now}))
        except ConnectionError:
            return   # advisory: retry on the next poll
        self._prefix_pub = memo
        self._prefix_pub_t = now

    def _sink(self, comp) -> None:
        """Commit one completion.  This write is the commit point of the
        exactly-once contract: a replica that dies before it leaves no
        trace, and the router redispatches."""
        if faults.corrupt_canary(str(comp.rid)):
            # injected green-pool wrongness: the replica warms, beats,
            # and serves CORRUPT output — exactly what the blue-green
            # canary exact-check must catch before traffic shifts
            tokens = np.asarray(comp.tokens, np.int32)
            tokens = (tokens + 1 if tokens.size
                      else np.asarray([1], np.int32))
            comp = dataclasses.replace(comp, tokens=tokens)
        if faults.corrupt_probe(str(comp.rid)):
            # injected golden-probe wrongness: a quarantined replica
            # that is still corrupt when re-probed (the reinstatement
            # gate must hold it out; enough of these retires it)
            tokens = np.asarray(comp.tokens, np.int32)
            tokens = (tokens + 1 if tokens.size
                      else np.asarray([1], np.int32))
            comp = dataclasses.replace(comp, tokens=tokens)
        handoff_ref = None
        if comp.reason == "handoff" and comp.handoff is not None:
            # disaggregated handoff, publish-then-commit: the KV payload
            # crosses the transport FIRST, then the done record (with
            # the ref) commits.  A death in the window — exactly what
            # KILL_AT_HANDOFF injects below — leaves no done key, so the
            # router re-runs the prefill elsewhere: at-least-once
            # publish under an exactly-once commit, with greedy
            # determinism collapsing any re-run to identical output.
            doc = dict(comp.handoff)
            doc["key"] = str(comp.rid)
            try:
                handoff_ref, _ = self.kv_transport.publish(
                    str(comp.rid), doc)
            except ConnectionError:
                # coord brownout mid-publish: commit WITHOUT a ref —
                # the decode side re-prefills (exact), nothing is lost
                log.warning("replica %s: KV publish for %s failed; "
                            "decode side will re-prefill",
                            self.replica_id, comp.rid)
            faults.on_handoff_published()
        elif comp.reason == "migrate":
            # live migration, publish-then-commit like the handoff seam:
            # the exported KV crosses the transport first (kind="migrate"
            # routes it through the MIGRATE_DROP knob), then the commit
            # carries the ref.  A SIGKILL in the window — KILL_AT_MIGRATE
            # — leaves no done key; the router's death sweep redispatches
            # the request whole, and greedy determinism makes the re-run
            # byte-identical.  Queued/mid-prefill evacuations arrive
            # payload-less and commit ref-less: the redispatch
            # re-prefills.
            if comp.handoff is not None:
                doc = dict(comp.handoff)
                doc["key"] = str(comp.rid)
                try:
                    handoff_ref, _ = self.kv_transport.publish(
                        str(comp.rid), doc, kind="migrate")
                except ConnectionError:
                    log.warning("replica %s: KV publish for migrating "
                                "%s failed; target will re-prefill",
                                self.replica_id, comp.rid)
            faults.on_migrate_published()
        payload = _encode_completion(
            self.replica_id, comp, handoff_ref=handoff_ref,
            adopt_fallback=str(comp.rid) in self._fallback_rids)
        self._fallback_rids.discard(str(comp.rid))
        # injected wire corruption: flip a bit in the ENCODED frame, so
        # the router-side checksum — not any replica-side check — is
        # the thing that has to catch it
        payload = faults.flip_wire_bits(payload)
        done_key = f"{self.ns}/done/{comp.rid}"
        try:
            self._flush_done_buffer()
            if self._done_buf:   # still down: keep commit order
                raise ConnectionError("coord store still unreachable")
            self.client.set(done_key, payload)
        except ConnectionError:
            if len(self._done_buf) >= self._done_buf_cap:
                dropped, _ = self._done_buf.pop(0)
                log.warning("replica %s: done buffer full during coord "
                            "outage; dropping oldest (%s) — the router "
                            "will redispatch it", self.replica_id,
                            dropped)
            self._done_buf.append((done_key, payload))
        self._served += 1
        trace = self._traces.pop(str(comp.rid), None)
        if trace is not None:
            # the exactly-once commit point, in the timeline: everything
            # after this is router-side consumption
            obs.events.record("done_commit", trace=trace.trace_id,
                              replica=self.replica_id, reason=comp.reason,
                              tokens=int(np.asarray(comp.tokens).size))

    def pool_drained(self) -> bool | None:
        pool = self.loop.pool
        if pool is None:
            return None
        pool.check()
        return pool.free_blocks == pool.num_blocks

    def tier_drained(self) -> bool | None:
        """Host-tier invariants + emptiness for the exit report
        (``None`` when the loop has no tier)."""
        fn = getattr(self.loop, "tier_drained", None)
        return fn() if fn is not None else None

    def serve(self) -> None:
        self.register()
        # registered but not yet heartbeating: the joiner-death window
        # the router's registration grace must bound (KILL_AT_WARMUP
        # dies here — registration persists, no lease ever appears)
        faults.on_warmup()
        self._hb.start(0)
        self._pub.start()
        self._pub.publish()   # immediate: the router gates on load info
        self._epub.start()
        clean = False
        try:
            self.loop.run((), source=self._source, sink=self._sink,
                          idle_wait_s=self.idle_wait_s)
            clean = True
        finally:
            try:
                # release the warm prefix cache + host tier before the
                # drain checks: exit-report drained means the WHOLE KV
                # hierarchy unwound, not just the live slots
                flush = getattr(self.loop, "flush_prefix_cache", None)
                if flush is not None:
                    flush()
            except Exception:
                pass
            try:
                self.client.set(
                    f"{self.ns}/exit/{self.replica_id}",
                    wire.encode_record("heartbeat", {
                        "replica": self.replica_id,
                        "served": self._served,
                        "pool_drained": self.pool_drained(),
                        "tier_drained": self.tier_drained(),
                        "weights_version": self._weights_version,
                        "clean": clean}))
            except Exception:
                pass
            self._pub.stop(final_publish=True)
            self._epub.stop(final_publish=True)
            self._hb.stop(graceful=True)


# -- the router side -------------------------------------------------------

class Router:
    """Health-aware least-loaded request router over the fleet namespace.

    See the module docstring for the failure model.  One ``Router``
    instance serialises one stream of requests; run several routers on
    disjoint namespaces for more.

    Args:
      client: coord client (the router's own; not shared with threads).
      namespace: fleet namespace prefix in the KV store.
      poll_s: idle poll interval of :meth:`run`'s event loop.
      max_redispatch: death-redispatches per request before it completes
        with ``reason="failed"`` (rejection re-routes are not counted —
        they are bounded by ``timeout_s``, not by attempts).
      reject_backoff_s: admission backoff applied to a replica whose
        published ``serve/rejected`` counter grew (it is shedding load).
      stale_after_s / lost_after_s: publish-age bounds handed to the
        health monitor (scaled for serve cadence, not training's).
      slo_quantile: the ``serve/queue_wait_s`` percentile used for SLO
        admission.  A deadline-bearing request is shed at the router
        (``reason="shed"``) when even the BEST candidate's published
        queue-wait at this quantile predicts the deadline is already
        unmeetable — before the request costs any replica a prefill.
        Replicas with no wait samples yet predict 0 (admit; the
        replica-side deadline kill still bounds the damage).
    """

    def __init__(self, client: CoordClient, *,
                 namespace: str = DEFAULT_NAMESPACE,
                 poll_s: float = 0.02,
                 max_redispatch: int = 8,
                 reject_backoff_s: float = 0.25,
                 stale_after_s: float = 3.0,
                 lost_after_s: float = 10.0,
                 slo_quantile: float = 0.99,
                 join_grace_s: float = 30.0,
                 degrade_max_new: int | None = None,
                 use_health: bool = True,
                 journal: bool = True,
                 compact_every: int = 50,
                 outage_grace_s: float = 5.0,
                 pull_min_blocks: int = 2,
                 pull_timeout_s: float = 5.0,
                 rebalance_after_polls: int = 0,
                 rebalance_min_gap: int = 2,
                 rebalance_timeout_s: float = 5.0,
                 prefix_ttl_s: float | None = None,
                 quarantine: bool = True,
                 golden_probe: GoldenProbe | None = None,
                 quarantine_config: QuarantineConfig | None = None,
                 alerts=None,
                 clock=time.monotonic,
                 wall=time.time,
                 sleeper=time.sleep) -> None:
        self.client = client
        self.ns = namespace
        self.poll_s = float(poll_s)
        # optional AlertManager (tpudist.obs.alerts): when wired, the
        # router reads fleet-level degradation through the declarative
        # alert interface — a firing FleetDegraded rule arms the same
        # admission clamp a replica-advertised degraded flag does —
        # instead of growing another bespoke threshold probe
        self.alerts = alerts
        # injectable time sources: the offline fleet simulator
        # (tpudist.sim) runs this SAME event loop against a virtual
        # clock whose sleeper advances simulated replicas instead of
        # blocking — production keeps the defaults
        self._clock = clock
        self._wall = wall
        self._sleep = sleeper
        self.max_redispatch = int(max_redispatch)
        self.reject_backoff_s = float(reject_backoff_s)
        if not 0.0 < slo_quantile <= 1.0:
            raise ValueError(
                f"slo_quantile must be in (0, 1], got {slo_quantile}")
        self.slo_quantile = float(slo_quantile)
        self.join_grace_s = float(join_grace_s)
        self.degrade_max_new = (None if degrade_max_new is None
                                else int(degrade_max_new))
        # crash recovery: journal request lifecycle to {ns}/journal/*
        # (schema tpudist.journal/1) so a replacement router can rebuild
        # the outstanding-request table with Router.recover().  Journal
        # writes are best-effort (never block routing on a brownout);
        # terminal records are compacted away every `compact_every`
        # polls once delivered.
        self.journal = bool(journal)
        self.compact_every = int(compact_every)
        # coord-brownout degradation: a poll that dies on ConnectionError
        # marks the store down and is SKIPPED (no death verdicts on
        # blind data); after reconnect, death verdicts for ever-live
        # replicas are suppressed another `outage_grace_s` so leases
        # that lapsed server-side during the outage can re-establish
        self.outage_grace_s = float(outage_grace_s)
        # pull-mode global prefix cache: a prefill-stage request whose
        # longest peer coverage beats `pull_min_blocks` full KV blocks
        # — and whose covering peer is NOT dispatchable — parks in a
        # "pull" stage while the owner exports its pages over the KV
        # transport; `pull_timeout_s` (or the owner's death) reverts it
        # to an ordinary prefill.  A pull can delay a request, never
        # lose one.
        self.pull_min_blocks = int(pull_min_blocks)
        self.pull_timeout_s = float(pull_timeout_s)
        # hot/cold rebalancing: after `rebalance_after_polls` consecutive
        # polls showing the SAME replica at least `rebalance_min_gap`
        # outstanding requests above the coolest candidate (or with a
        # published queue wait >= 2x the coolest's), the router asks the
        # hot replica to migrate its oldest in-flight request out via a
        # {ns}/migrate_req control key.  0 (the default) disables it —
        # the least-loaded score then remains admission-time-only.
        self.rebalance_after_polls = int(rebalance_after_polls)
        self.rebalance_min_gap = int(rebalance_min_gap)
        self.rebalance_timeout_s = float(rebalance_timeout_s)
        self._skew_streak: tuple[str, int] | None = None  # (hot rid, n)
        self._migrating: dict[str, float] = {}   # entry key -> cooldown
        self.prefix_dir = PrefixDirectory(client, namespace=namespace,
                                          ttl_s=prefix_ttl_s, wall=wall)
        self._journal_docs: dict[str, dict] = {}
        self._polls = 0
        self._coord_down_since: float | None = None
        self._outage_grace_until = float("-inf")
        self._health = (HealthMonitor(
            client=client, namespace=f"{namespace}/metrics",
            signal="serve/queue_wait_s", skew_threshold=4.0,
            stale_after_s=stale_after_s, lost_after_s=lost_after_s,
            confirm_n=2, recover_n=1) if use_health else None)
        self._seq = 0
        self._dead: set[str] = set()
        self._known: set[str] | None = None  # live set at first poll +
        #   every member seen since; later arrivals are JOINS
        self._backoff: dict[str, float] = {}           # rid -> until (mono)
        self._rejected_seen: dict[str, float] = {}     # rid -> watermark
        # registration→first-heartbeat grace bookkeeping: when each
        # registration was FIRST observed, and which rids have ever held
        # a lease (grace only shields never-live joiners — a member that
        # heartbeat once and stops is a real death, not a slow warmup)
        self._reg_seen: dict[str, float] = {}
        self._ever_live: set[str] = set()
        self._last_pool: str | None = None
        self._pool_gen = 0
        self._obs_requests = obs.counter("router/requests", unit="reqs")
        self._obs_dispatched = obs.counter("router/dispatched", unit="reqs")
        self._obs_completions = obs.counter("router/completions",
                                            unit="reqs")
        self._obs_redispatched = obs.counter("router/redispatched",
                                             unit="reqs")
        self._obs_rerouted = obs.counter("router/rejected_rerouted",
                                         unit="reqs")
        self._obs_deaths = obs.counter("router/replica_deaths",
                                       unit="replicas")
        self._obs_joins = obs.counter("router/joins", unit="replicas")
        self._obs_slo_shed = obs.counter("router/slo_shed", unit="reqs")
        self._obs_prefix_affinity = obs.counter("router/prefix_affinity",
                                                unit="reqs")
        self._obs_drains = obs.counter("router/drains", unit="replicas")
        self._obs_rolls = obs.counter("router/structural_rolls",
                                      unit="rolls")
        self._obs_rollbacks = obs.counter("router/rollbacks", unit="rolls")
        self._obs_degrade_clamped = obs.counter("router/degrade_clamped",
                                                unit="reqs")
        self._obs_recoveries = obs.counter("router/recoveries",
                                           unit="recoveries")
        self._obs_replays = obs.counter("router/recovered_replays",
                                        unit="reqs")
        self._obs_dup_terminals = obs.counter("router/dup_terminals",
                                              unit="reqs")
        self._obs_compactions = obs.counter("router/journal_compactions",
                                            unit="records")
        self._obs_orphans = obs.counter("router/orphans_swept",
                                        unit="keys")
        self._obs_outage_polls = obs.counter("router/outage_polls",
                                             unit="polls")
        # disaggregated two-stage scheduling: handoff consumptions
        # (prefill done -> decode dispatch) and the per-stage depth of
        # the outstanding set — the two pools' load signals
        self._obs_handoffs = obs.counter("router/handoffs", unit="reqs")
        # live KV migration: migrate commits consumed (preemption
        # overflow, rebalance, fast drain), migrations that lost their
        # payload (ref-less commit or adopt-side fetch miss — the
        # request re-prefilled, slower but byte-identical), and
        # rebalance intents issued
        self._obs_migrations = obs.counter("router/migrations",
                                           unit="reqs")
        self._obs_migration_fallbacks = obs.counter(
            "router/migration_fallbacks", unit="reqs")
        self._obs_rebalances = obs.counter("router/rebalances",
                                           unit="reqs")
        self._obs_stage_depth = {
            stage: obs.gauge(f"router/stage_depth~stage={stage}",
                             unit="reqs")
            for stage in ("prefill", "decode")}
        # fleet-global prefix cache: pull-mode exports initiated, and
        # pulls that came back empty / timed out / lost their owner
        # (the request re-prefills — slower, still exact)
        self._obs_prefix_pulls = obs.counter("router/prefix_pulls",
                                             unit="reqs")
        self._obs_pull_fallbacks = obs.counter(
            "router/prefix_pull_fallbacks", unit="reqs")
        # data-plane integrity: payloads that failed checksum/schema
        # verification at a router decode site, and corrupt-segment
        # verdicts replicas reported in-band.  Both feed the quarantine
        # manager's strike ledger.
        self._obs_checksum = obs.counter("integrity/checksum_mismatch",
                                         unit="payloads")
        self._obs_corrupt_seg = obs.counter("integrity/corrupt_segment",
                                            unit="segments")
        # golden probes need known-exact output: without `golden_probe`
        # the manager still quarantines (exclusion is the safe default)
        # but has no evidence path to reinstatement
        self.quarantine = (QuarantineManager(
            client, namespace=namespace, golden=golden_probe,
            config=quarantine_config, clock=clock)
            if quarantine else None)
        self._obs_journal = obs.gauge("router/journal_records",
                                      unit="records")
        self._obs_live = obs.gauge("router/replicas_live", unit="replicas")
        self._obs_outstanding = obs.gauge("router/outstanding", unit="reqs")
        self._obs_pool = obs.gauge("router/pool", unit="generation")
        self._obs_degraded = obs.gauge("router/degraded", unit="bool")
        # per-reason terminal-decision counters: how each request LEFT
        # the router (completed normally, shed at admission, timed out,
        # failed past max_redispatch) plus the non-terminal re-route.
        # Surfaced by loads()' fleet view and the bench JSONL.
        self._obs_decisions = {
            reason: obs.counter(
                f"router/decisions/{reason}", unit="reqs",
                help=f"requests resolved by the router as {reason!r}")
            for reason in ("completed", "shed", "rejected", "failed",
                           "timeout")}

    def _decide(self, reason: str, e: dict | None = None,
                **fields) -> None:
        """Count a routing decision, feed the SLO tracker, and (for a
        traced request) append the matching timeline event."""
        c = self._obs_decisions.get(reason)
        if c is not None:
            c.inc()
        if reason != "rejected":   # re-routes are not terminal outcomes
            req = (e or {}).get("req")
            obs.slo.observe(reason if reason != "completed"
                            else fields.get("serve_reason", "stop"),
                            priority=int(getattr(req, "priority", 0) or 0))
        trace = (e or {}).get("trace")
        if trace is not None:
            kind = {"completed": "done", "rejected": "reroute"}.get(
                reason, reason)
            obs.events.record(kind, trace=trace.trace_id, **fields)

    def decisions(self) -> dict[str, float]:
        """Per-reason terminal decision counts (plus re-routes under
        ``rejected``): ``{reason: count}``."""
        return {reason: c.value()
                for reason, c in self._obs_decisions.items()}

    # -- fleet view --------------------------------------------------------

    def replicas(self) -> dict[str, dict]:
        """Registered replicas: ``{replica_id: registration info}``."""
        out = {}
        prefix = f"{self.ns}/replica/"
        for key in self.client.keys(prefix):
            raw = self.client.get(key)
            if raw is not None:
                out[key[len(prefix):]] = json.loads(raw.decode())
        return out

    def live(self) -> set[str]:
        """Replica ids currently holding a heartbeat lease."""
        mark = f"{self.ns}:"
        return {name[len(mark):] for name in self.client.live()
                if name.startswith(mark)}

    def draining(self) -> set[str]:
        """Replica ids marked for graceful drain (``{ns}/draining/{rid}``
        — set by the autoscaler's scale-down or a blue-green commit):
        the router stops dispatching to them, their in-flight work
        finishes, and their eventual departure counts as a DRAIN, not a
        death."""
        prefix = f"{self.ns}/draining/"
        try:
            return {k[len(prefix):] for k in self.client.keys(prefix)}
        except ConnectionError:
            return set()

    def _active_pool(self) -> str | None:
        """The pool tag traffic is pinned to (``{ns}/pool`` key), or
        ``None`` before any structural rollout — every pool eligible."""
        try:
            raw = self.client.get(f"{self.ns}/pool")
        except ConnectionError:
            return self._last_pool
        return raw.decode() if raw is not None else None

    def loads(self, regs: dict[str, dict]) -> dict[str, dict]:
        """Published load per replica id: queue depth + free KV blocks
        gauges, the lifetime queue-wait mean and ``slo_quantile``
        percentile (the SLO-admission predictor), the weights version,
        and the mid-hot-swap flag."""
        rank_to_rid = {int(info.get("rank", -1)): rid
                       for rid, info in regs.items()}
        out: dict[str, dict] = {}
        for rank, snap in collect(self.client,
                                  f"{self.ns}/metrics").items():
            rid = rank_to_rid.get(rank)
            if rid is None:
                continue
            gauges = snap.get("gauges", {})
            counters = snap.get("counters", {})
            wait = snap.get("histograms", {}).get("serve/queue_wait_s")
            out[rid] = {
                "queue_depth": (gauges.get("serve/queue_depth")
                                or {}).get("value") or 0.0,
                "kv_blocks_free": (gauges.get("serve/kv_blocks_free")
                                   or {}).get("value"),
                "queue_wait_mean": (wait["sum"] / wait["count"]
                                    if wait and wait["count"] else 0.0),
                "queue_wait_q": (hist_quantile(wait, self.slo_quantile)
                                 if wait and wait["count"] else 0.0),
                "rejected": (counters.get("serve/rejected")
                             or {}).get("value") or 0.0,
                "timeouts": (counters.get("serve/timeouts")
                             or {}).get("value") or 0.0,
                "swapping": bool((gauges.get("serve/swapping")
                                  or {}).get("value") or 0.0),
                "degraded": bool((gauges.get("serve/degraded")
                                  or {}).get("value") or 0.0),
                "weights_version": (gauges.get("serve/weights_version")
                                    or {}).get("value"),
                # RTT-amortization factor per replica: how many tokens
                # the last drained dispatch generated, and the lifetime
                # host round-trip count (fused multi-token decode)
                "steps_per_dispatch": (
                    gauges.get("serve/steps_per_dispatch")
                    or {}).get("value"),
                "dispatches": (counters.get("serve/dispatches")
                               or {}).get("value") or 0.0,
                "age_s": snap.get("age_s"),
            }
        return out

    def _update_backoffs(self, loads: dict[str, dict]) -> None:
        """A replica whose ``serve/rejected`` counter grew is shedding:
        pause new admissions to it briefly instead of feeding the shed."""
        now = self._clock()
        for rid, l in loads.items():
            seen = self._rejected_seen.get(rid, 0.0)
            if l["rejected"] > seen:
                self._backoff[rid] = now + self.reject_backoff_s
            self._rejected_seen[rid] = l["rejected"]

    def _prefix_map(self, candidates: Sequence[str]) -> dict[str, set[int]]:
        """One read of every candidate's published prefix-affinity
        summary, once per poll, through the fleet directory (which
        applies the ``TPUDIST_PREFIX_SUMMARY_TTL_S`` staleness bound —
        a dead-but-registered replica's last publish must not keep
        attracting affinity traffic).  Corrupt, missing, or stale
        summaries degrade to no-affinity — the hash steer is advisory,
        the least-loaded tie-break still places the request."""
        self.prefix_dir.refresh(candidates)
        return self.prefix_dir.affinity(candidates)

    def _pick(self, candidates: Sequence[str], loads: dict[str, dict],
              assigned: dict[str, int],
              prefix_hash: int | None = None,
              prefix_map: dict[str, set[int]] | None = None) -> str | None:
        """Least-loaded with prefix affinity: replicas whose published
        prefix-cache summary holds the request's prefix hash sort ahead
        (their shared KV pages make the admission nearly prefill-free),
        then fewest known-outstanding work (the router's own
        assignments are fresher than any published gauge), then
        shortest published queue wait, then most free KV blocks (a
        dense replica has no block limit and sorts as infinite)."""
        best, best_score = None, None
        for rid in candidates:
            l = loads.get(rid, {})
            free = l.get("kv_blocks_free")
            hit = (prefix_hash is not None and prefix_map is not None
                   and prefix_hash in prefix_map.get(rid, ()))
            score = (
                0 if hit else 1,
                assigned.get(rid, 0) + l.get("queue_depth", 0.0),
                l.get("queue_wait_mean", 0.0),
                -(free if free is not None else float("inf")),
            )
            if best_score is None or score < best_score:
                best, best_score = rid, score
        if best is not None and best_score[0] == 0:
            self._obs_prefix_affinity.inc()
        return best

    # -- hot/cold rebalancing ----------------------------------------------

    @staticmethod
    def rebalance_hot_cold(loads: dict[str, dict],
                           candidates: Sequence[str],
                           assigned: dict[str, int], *,
                           min_gap: int = 2) -> tuple[str, str] | None:
        """``(hot, cold)`` when one candidate carries at least
        ``min_gap`` more outstanding work (router assignments + its
        published queue depth) than the coolest — or advertises a
        queue-wait percentile at least 2x the coolest's non-zero one.
        Pure: the skew signal is unit-testable on synthetic loads."""
        if len(candidates) < 2:
            return None

        def depth(rid: str) -> float:
            return assigned.get(rid, 0) + (
                loads.get(rid, {}).get("queue_depth") or 0.0)

        hot = max(candidates, key=depth)
        cold = min(candidates, key=depth)
        if hot == cold:
            return None
        hot_wait = loads.get(hot, {}).get("queue_wait_q") or 0.0
        cold_wait = loads.get(cold, {}).get("queue_wait_q") or 0.0
        if (depth(hot) - depth(cold) >= min_gap
                or (cold_wait > 0.0 and hot_wait >= 2.0 * cold_wait)):
            return hot, cold
        return None

    @staticmethod
    def rebalance_victim(entries: dict[str, dict], done: dict,
                         hot: str, migrating=()) -> str | None:
        """The OLDEST outstanding request assigned to the hot replica
        (smallest dispatch key — the longest-running decode, whose
        remaining work is most worth moving) not already
        mid-migration."""
        keys = sorted(k for k, e in entries.items()
                      if k not in done and k not in migrating
                      and e.get("assigned") == hot
                      and e.get("stage", "prefill") != "pull")
        return keys[0] if keys else None

    def _sweep_dead(self, rid: str, regs: dict[str, dict]) -> None:
        """Remove a dead replica's coordination residue so restarted
        ids and fresh health rounds start clean."""
        for key in (list(self.client.keys(f"{self.ns}/inbox/{rid}/"))
                    # pending pull requests addressed to the dead
                    # owner: nobody will answer them (the waiting
                    # entries revert to prefill on their pull timeout)
                    + list(self.client.keys(f"{self.ns}/pullreq/{rid}/"))
                    # unconsumed migrate intents: the outstanding work
                    # is redispatched below anyway, and a replica
                    # reusing the id must not inherit stale evictions
                    + list(self.client.keys(
                        f"{self.ns}/migrate_req/{rid}/"))):
            try:
                self.client.delete(key)
            except ConnectionError:
                pass
        for key in (f"{self.ns}/replica/{rid}",
                    f"{self.ns}/metrics/{regs.get(rid, {}).get('rank')}",
                    f"{self.ns}/draining/{rid}",
                    f"{self.ns}/prefix/{rid}",
                    f"{self.ns}/quarantined/{rid}"):
            try:
                self.client.delete(key)
            except ConnectionError:
                pass
        if self.quarantine is not None:
            # its quarantine record dies with it: a future replica
            # reusing the id starts with a clean strike ledger
            self.quarantine.drop(rid)

    # -- crash-recovery journal --------------------------------------------
    #
    # One record per request at {ns}/journal/{key}, written full-record
    # (idempotent) at each lifecycle transition.  Write-ordering
    # invariants (see docs/DESIGN.md "Control-plane recovery"):
    #
    #   * dispatch: inbox set FIRST, then journal assigned-update — a
    #     crash in the window leaves the record open-unassigned, so
    #     recovery redispatches; under greedy determinism a resulting
    #     double-serve commits an identical duplicate done key, which
    #     consumption dedupes.
    #   * terminal: read done key -> journal terminal (WITH tokens) ->
    #     delete done key -> deliver.  Consumption is journaled before
    #     the done key is destroyed, so "journal open + no done key"
    #     always means the replica has not committed yet (safe to keep
    #     waiting), never "the outcome was consumed and lost".
    #
    # Journal writes are best-effort: a brownout skips them (routing
    # must not stall on the journal) and the record catches up on the
    # next transition's full-record write.

    def _journal_key(self, k: str) -> str:
        return f"{self.ns}/journal/{k}"

    def _journal_write(self, k: str) -> None:
        if not self.journal:
            return
        doc = self._journal_docs.get(k)
        if doc is None:
            return
        try:
            self.client.set(self._journal_key(k),
                            wire.encode_record("journal", doc))
        except ConnectionError:
            pass

    def _journal_submit(self, entries: dict[str, dict]) -> None:
        """Journal every request at submit time (terminal=None,
        unassigned), so recovery needs only the store — arrival
        schedules and caller rids ride in the record."""
        if not self.journal:
            return
        for k, e in entries.items():
            req = e["req"]
            self._journal_docs[k] = {
                "schema": JOURNAL_SCHEMA,
                "req": _request_doc(k, req),
                "rid": str(req.rid),
                "assigned": None,
                "attempts": 0,
                "at": float(e.get("at", 0.0)),
                "terminal": None,
            }
            self._journal_write(k)
        self._obs_journal.set(len(self._journal_docs))

    def _journal_assign(self, k: str, e: dict) -> None:
        doc = self._journal_docs.get(k)
        if doc is None:
            return
        doc["assigned"] = e["assigned"]
        doc["attempts"] = int(e["attempts"])
        self._journal_write(k)

    def _journal_handoff(self, k: str, e: dict, *,
                         stage: str = "decode") -> None:
        """The stage transition's journal record: the new stage plus the
        payload ref, written BEFORE the prefill done key is destroyed —
        a router crash in between recovers into a decode-stage entry
        and redispatches it exactly once (to the decode pool, payload
        ref intact; a lost payload degrades to re-prefill, never to a
        lost or doubled request).  Migrate commits ride the same record
        with ``stage="decode"`` (payload exported) or ``"prefill"``
        (ref-less: the redispatch re-prefills)."""
        doc = self._journal_docs.get(k)
        if doc is None:
            return
        doc["stage"] = stage
        doc["handoff_ref"] = e.get("handoff_ref")
        doc["assigned"] = None
        doc["attempts"] = int(e["attempts"])
        self._journal_write(k)

    def _journal_pull(self, k: str, e: dict) -> None:
        """Journal a pull-stage transition (initiation: stage="pull";
        resolution: stage back to "prefill" with the payload ref, or
        without one on a fallback).  A router crash mid-pull recovers
        the entry as an ordinary prefill — the pull was an
        optimization, the request's exactly-once contract never
        depended on it."""
        doc = self._journal_docs.get(k)
        if doc is None:
            return
        doc["stage"] = e.get("stage", "prefill")
        doc["prefix_ref"] = e.get("prefix_ref")
        doc["assigned"] = None
        doc["attempts"] = int(e["attempts"])
        self._journal_write(k)

    def _journal_terminal(self, k: str, reason: str, tokens,
                          serve_reason: str | None = None) -> None:
        doc = self._journal_docs.get(k)
        if doc is None:
            return
        doc["terminal"] = reason
        doc["serve_reason"] = serve_reason
        doc["tokens"] = np.asarray(tokens).astype(int).tolist()
        doc["assigned"] = None
        self._journal_write(k)

    def _compact_journal(self, done: dict) -> None:
        """Delete journal records for DELIVERED terminals — the journal
        stays bounded by the outstanding set, not by run length."""
        if not self.journal:
            return
        for k in [k for k, doc in self._journal_docs.items()
                  if doc.get("terminal") is not None and k in done]:
            try:
                self.client.delete(self._journal_key(k))
            except ConnectionError:
                continue   # keep the doc; retried next compaction
            del self._journal_docs[k]
            self._obs_compactions.inc()
        self._obs_journal.set(len(self._journal_docs))

    # -- the event loop ----------------------------------------------------

    def run(self, requests: Sequence[Any], *,
            timeout_s: float = 120.0,
            arrivals: Sequence[float] | None = None,
            on_complete=None) -> list[Any]:
        """Route ``requests`` across the fleet; returns one
        :class:`~tpudist.models.serving.Completion` per request, in
        FINISH order, with each completion's ``rid`` restored to the
        caller's.  Raises :class:`TimeoutError` after ``timeout_s`` —
        the no-hang bound for total-fleet loss.

        ``arrivals`` (one offset in seconds per request, from run
        start) replays a TIMED workload through the same submit path:
        each request becomes visible to dispatch — and its trace is
        minted — only once its offset elapses, so a scenario's diurnal
        ramp or flash crowd hits the fleet with its real shape instead
        of as one up-front batch.

        ``on_complete(key, completion)`` is invoked as each terminal
        decision lands (AFTER its journal terminal record) — the
        incremental delivery hook the ``--route`` CLI uses to stream
        results to disk so a crashed router's successor knows what was
        already delivered."""
        if arrivals is not None and len(arrivals) != len(requests):
            raise ValueError(
                f"arrivals ({len(arrivals)}) must match requests "
                f"({len(requests)})")
        entries: dict[str, dict] = {}
        for i, req in enumerate(requests):
            key = f"{self._seq:08d}"
            self._seq += 1
            at = 0.0 if arrivals is None else max(0.0, float(arrivals[i]))
            entries[key] = {"req": req, "assigned": None, "attempts": 0,
                            "trace": None, "at": at, "arrived": False}
        self._journal_submit(entries)
        return self._drive(entries, timeout_s=timeout_s,
                           on_complete=on_complete)

    def recover(self, *, timeout_s: float = 120.0,
                delivered: Sequence[str] = (),
                on_complete=None) -> list[Any]:
        """Rebuild the outstanding-request table from ``{ns}/journal/*``
        + done keys and drive it to completion — the crashed-router
        failover path.  Live replicas are RE-ADOPTED without a restart
        (their open assignments stay assigned; their committed done
        keys are consumed normally); assignments to dead replicas flow
        through the ordinary death-redispatch machinery on the first
        poll; orphaned inbox entries (assigned elsewhere, or already
        terminal) are swept; terminal-journaled requests are replayed
        from their stored tokens — unless their caller rid is in
        ``delivered`` (rids the previous router already delivered, e.g.
        read back from the ``--results`` file) — and any duplicate done
        key they left behind is deleted and counted
        (``router/dup_terminals``).  Returns replayed + newly finished
        completions in finish order."""
        from tpudist.models.serving import Completion

        self._obs_recoveries.inc()
        seen_delivered = {str(r) for r in delivered}
        prefix = f"{self.ns}/journal/"
        records: dict[str, dict] = {}
        for key in self.client.keys(prefix):
            raw = self.client.get(key)
            if raw is None:
                continue
            try:
                doc = wire.decode_record(raw, expect="journal",
                                         namespace=self.ns,
                                         key=key[len(prefix):])
            except wire.WireError as err:
                # a corrupt journal record cannot be recovered FROM —
                # count it and skip; the request it described either
                # has a live done key (consumed normally) or is lost
                # to this recovery, never a poll-loop crash
                self._obs_checksum.inc()
                log.warning("router: skipping corrupt journal record "
                            "%s (%s)", key, err.reason)
                continue
            if doc.get("schema") != JOURNAL_SCHEMA:
                continue
            records[key[len(prefix):]] = doc
        # never mint a key that could collide with a journaled one
        for k in records:
            try:
                self._seq = max(self._seq, int(k) + 1)
            except ValueError:
                pass
        self._journal_docs = dict(records)
        self._obs_journal.set(len(records))
        entries: dict[str, dict] = {}
        replays: list[tuple[str, Any]] = []
        for k in sorted(records):
            doc = records[k]
            rid = str(doc.get("rid", k))
            if doc.get("terminal") is not None:
                # the decision was made (and journaled) before the
                # crash: replay it from the stored tokens rather than
                # re-running, and delete the duplicate done key a
                # falsely-presumed-dead replica may have left
                try:
                    if self.client.get(f"{self.ns}/done/{k}") is not None:
                        self.client.delete(f"{self.ns}/done/{k}")
                        self._obs_dup_terminals.inc()
                except ConnectionError:
                    pass
                if rid in seen_delivered:
                    # terminal AND already delivered: nothing left to
                    # do — compact the record away right now
                    try:
                        self.client.delete(self._journal_key(k))
                        del self._journal_docs[k]
                        self._obs_compactions.inc()
                    except ConnectionError:
                        pass
                    continue
                replays.append((k, Completion(
                    rid=rid,
                    prompt=np.asarray(doc["req"]["prompt"], np.int32),
                    tokens=np.asarray(doc.get("tokens", ()), np.int32),
                    reason=doc["terminal"])))
                continue
            req = dataclasses.replace(
                _decode_request(json.dumps(doc["req"]).encode()),
                rid=rid)
            tc = TraceContext.mint(k)
            stage = doc.get("stage", "prefill")
            if stage == "pull":
                # a pull was in flight when the router died: the pull
                # was an OPTIMIZATION — recover the request as an
                # ordinary prefill (the orphaned pullreq/pulldone keys
                # are residue a later poll sweeps)
                stage = "prefill"
            entries[k] = {"req": req,
                          "assigned": doc.get("assigned"),
                          "attempts": int(doc.get("attempts", 0)),
                          # a journaled handoff recovers mid-pipeline:
                          # stage=decode + the payload ref, so the
                          # replacement router dispatches straight to
                          # the decode pool (ref missing -> re-prefill)
                          "stage": stage,
                          "handoff_ref": doc.get("handoff_ref"),
                          "prefix_ref": doc.get("prefix_ref"),
                          "trace": tc, "at": 0.0, "arrived": True}
            obs.events.record("recover_adopt", trace=tc.trace_id,
                              key=k, rid=rid,
                              assigned=doc.get("assigned"),
                              attempts=int(doc.get("attempts", 0)))
        if replays:
            self._obs_replays.inc(len(replays))
        # sweep orphaned inbox entries: anything not matching an open
        # journal assignment is residue of the crashed router (a
        # terminal request's leftover dispatch, or a dispatch superseded
        # by a redispatch) — a replica must not serve it again
        inbox_prefix = f"{self.ns}/inbox/"
        for key in self.client.keys(inbox_prefix):
            rid_part, _, k = key[len(inbox_prefix):].partition("/")
            e = entries.get(k)
            if e is not None and e["assigned"] == rid_part:
                continue
            try:
                self.client.delete(key)
                self._obs_orphans.inc()
            except ConnectionError:
                pass
        log.info("router: recovered %d open + %d terminal journal "
                 "records (%d replayed)", len(entries),
                 len(records) - len(entries), len(replays))
        return self._drive(entries, timeout_s=timeout_s,
                           on_complete=on_complete, preloaded=replays)

    def _drive(self, entries: dict[str, dict], *, timeout_s: float,
               on_complete=None, preloaded: Sequence[tuple] = ()
               ) -> list[Any]:
        done: dict[str, Any] = {}
        finish: list[str] = []
        remaining = set(entries)

        def complete(key: str, comp) -> None:
            done[key] = comp
            finish.append(key)
            remaining.discard(key)
            self._obs_completions.inc()
            # payload lifecycle belongs to the ROUTER (the request's
            # owner): KV-migration and prefix-pull payloads die with
            # the request's terminal, whatever the terminal was — an
            # exporter death cannot leak them
            e = entries.get(key) or {}
            for ref in (e.get("handoff_ref"), e.get("prefix_ref")):
                if ref:
                    try:
                        self.client.delete(ref)
                    except ConnectionError:
                        pass
            if on_complete is not None:
                on_complete(key, comp)

        for k, comp in preloaded:
            complete(k, comp)
        start = self._clock()
        deadline = start + timeout_s
        while remaining:
            if self._clock() > deadline:
                raise TimeoutError(
                    f"router: {len(remaining)} of "
                    f"{len(entries)} requests unresolved after "
                    f"{timeout_s:.0f}s (live replicas: "
                    f"{sorted(self._live_or(set()))})")
            progressed = self._arrive(entries, start) > 0
            try:
                progressed = (self._poll(entries, done, complete)
                              or progressed)
            except ConnectionError as err:
                # coord brownout: poll blind — keep in-flight decodes
                # running, make NO death verdicts, and retry.  The
                # store being unreachable is stale-not-lost, fleet-wide.
                if self._coord_down_since is None:
                    self._coord_down_since = self._clock()
                    log.warning("router: coord store unreachable (%s); "
                                "polling blind until it returns", err)
                self._obs_outage_polls.inc()
                progressed = False
            else:
                if self._coord_down_since is not None:
                    gap = self._clock() - self._coord_down_since
                    self._outage_grace_until = (self._clock()
                                                + self.outage_grace_s)
                    self._coord_down_since = None
                    log.info("router: coord store back after %.1fs; "
                             "suppressing death verdicts for %.1fs",
                             gap, self.outage_grace_s)
            self._polls += 1
            if (self.journal and self.compact_every > 0
                    and self._polls % self.compact_every == 0):
                self._compact_journal(done)
            self._obs_outstanding.set(len(remaining))
            if not progressed:
                self._sleep(self.poll_s)
        # sweep duplicate done keys (a presumed-dead replica may have
        # committed after its redispatch; greedy determinism makes the
        # duplicate identical, so it is just deleted), then compact the
        # journal to empty — every record is delivered now
        for key in entries:
            try:
                self.client.delete(f"{self.ns}/done/{key}")
            except ConnectionError:
                pass
        self._compact_journal(done)
        self._obs_outstanding.set(0)
        return [done[k] for k in finish]

    def _live_or(self, fallback: set[str]) -> set[str]:
        try:
            return self.live()
        except ConnectionError:
            return fallback

    def _arrive(self, entries: dict[str, dict], start: float) -> int:
        """Admit entries whose arrival offset has elapsed: mint the
        trace context — submit IS the trace root; it lives in the
        router entry (not just the request) so a redispatch re-sends
        the SAME context and the replica-side events of both attempts
        merge under one trace id — and record the enqueue event with
        the request's replayable shape (prompt length, budget,
        priority, relative deadline), which is what lets a recorded
        trace be turned back into a workload."""
        now = self._clock() - start
        n = 0
        for key, e in entries.items():
            if e.get("arrived", True) or e.get("at", 0.0) > now:
                continue
            e["arrived"] = True
            req = e["req"]
            tc = TraceContext.mint(key)
            e["trace"] = tc
            obs.events.record(
                "enqueue", trace=tc.trace_id, key=key,
                rid=str(req.rid),
                prompt_tokens=int(np.asarray(req.prompt).size),
                max_new=int(req.max_new_tokens),
                priority=int(getattr(req, "priority", 0) or 0),
                rel_deadline_s=(
                    None if req.deadline_s is None
                    else round(req.deadline_s - self._wall(), 6)))
            n += 1
        if n:
            self._obs_requests.inc(n)
        return n

    def _poll(self, entries: dict[str, dict], done: dict,
              complete) -> bool:
        from tpudist.models.serving import Completion

        faults.on_router_poll()
        progressed = False
        regs = self.replicas()
        live = self.live() - self._dead
        self._obs_live.set(len(live))
        now_mono = self._clock()
        self._ever_live |= live
        for rid in regs:
            self._reg_seen.setdefault(rid, now_mono)
        draining = self.draining()
        pool = self._active_pool()
        if pool != self._last_pool:
            self._pool_gen += 1
            log.info("router: active pool is now %r (generation %d)",
                     pool, self._pool_gen)
            self._last_pool = pool
        self._obs_pool.set(self._pool_gen)

        # live-join discovery: membership is re-read every poll, so a
        # replica that registered after this router started (or even
        # mid-run) takes traffic on the very next dispatch.  The first
        # poll's live set is the baseline fleet, not a join.
        if self._known is None:
            self._known = set(live)
        else:
            joined = live - self._known
            if joined:
                self._known |= joined
                self._obs_joins.inc(len(joined))
                log.info("router: replica(s) %s joined the fleet",
                         sorted(joined))

        # 1) consume completions FIRST: work a replica committed just
        # before dying must not be re-run
        quarantined = (self.quarantine.quarantined()
                       if self.quarantine is not None else set())

        def reroute(key: str, k: str, e: dict, replica: str,
                    reason: str) -> None:
            """Un-deliver one done key: destroy it, clear the
            assignment so dispatch re-routes, and back the replica
            off — the shared tail of every shed/integrity verdict."""
            self.client.delete(key)
            e["assigned"] = None
            self._journal_assign(k, e)
            self._obs_rerouted.inc()
            if replica:
                self._backoff[replica] = (self._clock()
                                          + self.reject_backoff_s)
            self._decide("rejected", e, replica=replica or None,
                         verdict=reason)

        done_prefix = f"{self.ns}/done/"
        for key in self.client.keys(done_prefix):
            k = key[len(done_prefix):]
            if k.startswith("probe-"):
                continue   # golden-probe answers: the quarantine
                #            manager consumes these, not the run loop
            e = entries.get(k)
            if e is None or k in done:
                continue
            raw = self.client.get(key)
            if raw is None:
                continue
            try:
                payload = wire.decode_record(
                    raw, expect="completion", namespace=self.ns,
                    key=k, replica=e["assigned"] or "")
            except wire.WireError as err:
                # a corrupt commit must never be delivered: count it,
                # strike the replica the payload was assigned to (the
                # bytes are untrustworthy, so attribution comes from
                # the router's own assignment table), and redispatch
                progressed = True
                self._obs_checksum.inc()
                log.warning("router: corrupt done payload %s (%s) "
                            "from replica %r; redispatching", k,
                            err.reason, err.replica)
                if self.quarantine is not None and err.replica:
                    self.quarantine.strike(err.replica,
                                           f"wire/{err.reason}")
                    quarantined = self.quarantine.quarantined()
                reroute(key, k, e, err.replica, "checksum_mismatch")
                continue
            req = e["req"]
            comp = Completion(
                rid=req.rid, prompt=np.asarray(req.prompt),
                tokens=np.asarray(payload.get("tokens", ()), np.int32),
                reason=str(payload.get("reason")))
            progressed = True
            replica = str(payload.get("replica") or "")
            if comp.reason == "rejected":
                # replica-side load shed: re-route, don't surface —
                # the request was admitted to the FLEET, and some other
                # replica (or this one, later) can still serve it
                reroute(key, k, e, replica, "rejected")
            elif comp.reason in ("corrupt_segment", "wire_error"):
                # in-band integrity verdicts: the replica caught its
                # own corruption (NaN-frozen lane / undecodable inbox
                # payload).  Same answer as a checksum mismatch —
                # strike + redispatch — just attributed by the replica
                # itself instead of by this router's verification.
                if comp.reason == "corrupt_segment":
                    self._obs_corrupt_seg.inc()
                else:
                    self._obs_checksum.inc()
                log.warning("router: replica %s reported %s for %s; "
                            "redispatching", replica, comp.reason, k)
                if self.quarantine is not None and replica:
                    self.quarantine.strike(replica, comp.reason)
                    quarantined = self.quarantine.quarantined()
                reroute(key, k, e, replica, comp.reason)
            elif replica and replica in quarantined:
                # a quarantined replica's commit: the checksum proves
                # the BYTES crossed intact, not that the compute behind
                # them did — a replica under integrity suspicion does
                # not get to deliver.  Redispatch to a trusted one
                # (greedy determinism dedupes any duplicate).
                reroute(key, k, e, replica, "quarantined")
            elif comp.reason == "handoff":
                # two-stage scheduling: a prefill replica finished its
                # half and migrated the KV.  NOT a terminal — flip the
                # entry to the decode stage and let dispatch place it
                # on the decode pool.  Journal-then-delete ordering
                # mirrors the terminal path: a crash in between
                # recovers a decode-stage record (payload ref intact)
                # and redispatches exactly once.
                e["stage"] = "decode"
                e["handoff_ref"] = payload.get("handoff_ref")
                e["assigned"] = None
                self._journal_handoff(k, e)
                self.client.delete(key)
                self._obs_handoffs.inc()
                trace = e.get("trace")
                if trace is not None:
                    obs.events.record("handoff", trace=trace.trace_id,
                                      from_replica=replica,
                                      ref=e["handoff_ref"])
            elif comp.reason == "migrate":
                # live migration: the replica evacuated this request
                # (preemption overflow, rebalance intent, fast drain).
                # NOT a terminal — with a payload ref the entry becomes
                # a decode-stage redispatch (the target adopts the
                # mid-decode pages and continues); ref-less (queued or
                # mid-prefill at export time, or the publish browned
                # out) it reverts to an ordinary prefill, byte-identical
                # under greedy determinism.  Same journal-then-delete
                # ordering as the handoff stage, so recover() resumes a
                # mid-migration request exactly once.
                ref = payload.get("handoff_ref")
                e["stage"] = "decode" if ref else "prefill"
                e["handoff_ref"] = ref
                e["assigned"] = None
                e["migrated"] = True
                self._journal_handoff(k, e, stage=e["stage"])
                self.client.delete(key)
                self._obs_migrations.inc()
                if not ref:
                    self._obs_migration_fallbacks.inc()
                self._migrating.pop(k, None)
                trace = e.get("trace")
                if trace is not None:
                    obs.events.record("migrate", trace=trace.trace_id,
                                      from_replica=replica, ref=ref)
            else:
                # commit-point ordering: journal the terminal (WITH the
                # tokens) before destroying the done key, so a crash in
                # between leaves a replayable record instead of an
                # outcome that was consumed and lost
                if e.get("migrated") and payload.get("adopt_fallback"):
                    # the migrated payload crossed but the adopting
                    # replica's fetch missed (drop-injected or expired):
                    # it re-prefilled — exact, but the migration's
                    # latency win was lost.  Count it.
                    self._obs_migration_fallbacks.inc()
                self._journal_terminal(k, comp.reason, comp.tokens)
                self.client.delete(key)
                complete(k, comp)
                self._decide("completed", e, serve_reason=comp.reason,
                             replica=payload.get("replica"),
                             tokens=int(np.asarray(comp.tokens).size))

        # 1.5) pull-mode global prefix cache: consume owner export
        # answers, expire stalled pulls.  Resolution either way flips
        # the entry back to prefill stage so dispatch places it — with
        # the payload ref when the export landed, without one (full
        # re-prefill, still exact) on any miss/timeout/owner-death.
        pd_prefix = f"{self.ns}/pulldone/"
        for key in self.client.keys(pd_prefix):
            k = key[len(pd_prefix):]
            raw = self.client.get(key)
            if raw is None:
                continue
            try:
                payload = wire.decode_record(raw, expect="pulldone",
                                             namespace=self.ns, key=k)
                ref = payload.get("ref")
            except wire.WireError:
                ref = None
            e = entries.get(k)
            if e is None or k in done or e.get("stage") != "pull":
                # residue: the request resolved some other way (timeout,
                # terminal, recovery) before the owner answered — the
                # published payload dies here, never leaks
                self.client.delete(key)
                if ref:
                    try:
                        self.client.delete(str(ref))
                    except ConnectionError:
                        pass
                continue
            e["stage"] = "prefill"
            e["prefix_ref"] = str(ref) if ref else None
            e["pull_deadline"] = None
            self._journal_pull(k, e)
            self.client.delete(key)
            progressed = True
            if not ref:
                self._obs_pull_fallbacks.inc()
            trace = e.get("trace")
            if trace is not None:
                obs.events.record("pull_done", trace=trace.trace_id,
                                  owner=e.get("pull_owner"),
                                  ref=e.get("prefix_ref"))
        for k, e in entries.items():
            if k in done or e.get("stage") != "pull":
                continue
            owner = e.get("pull_owner")
            deadline = e.get("pull_deadline") or 0.0
            if owner in live and now_mono <= deadline:
                continue
            # stalled pull: the owner died, drained, or is just slow —
            # stop waiting and dispatch as an ordinary prefill (the
            # late answer, if any, is swept as residue above)
            e["stage"] = "prefill"
            e["prefix_ref"] = None
            e["pull_deadline"] = None
            self._journal_pull(k, e)
            self._obs_pull_fallbacks.inc()
            progressed = True
            try:
                self.client.delete(f"{self.ns}/pullreq/{owner}/{k}")
            except ConnectionError:
                pass
            log.info("router: pull for %s from %s %s; falling back to "
                     "re-prefill", k, owner,
                     "timed out" if owner in live else "lost its owner")
            trace = e.get("trace")
            if trace is not None:
                obs.events.record("pull_fallback", trace=trace.trace_id,
                                  owner=owner)

        # 2) death detection + drain/redispatch
        verdict_lost: set[str] = set()
        if self._health is not None:
            try:
                self._health.update()
                rank_to_rid = {int(info.get("rank", -1)): rid
                               for rid, info in regs.items()}
                verdict_lost = {
                    rank_to_rid[int(r)]
                    for r in self._health.verdict().get("lost", [])
                    if int(r) in rank_to_rid}
            except (ConnectionError, ValueError):
                pass
        assigned_to = {e["assigned"] for e in entries.values()
                       if e["assigned"] is not None}
        # scan every rid with assigned work OR a registration: a
        # registered replica whose lease is gone is swept even when
        # idle, so a joiner that died at warmup doesn't pin its
        # registration (and rank/metrics slot) forever
        for rid in sorted((assigned_to | set(regs)) - self._dead):
            lost = rid in verdict_lost
            if rid in live and not lost:
                continue
            if now_mono < self._outage_grace_until \
                    and rid in self._ever_live:
                # post-brownout grace: leases lapsed server-side while
                # the STORE was down; give every ever-live replica one
                # grace window to re-beat before calling it dead — an
                # outage must not become a mass-death redispatch storm
                continue
            if not lost and rid not in self._ever_live:
                # registration→first-heartbeat grace: a slow-warming
                # joiner (jax import + compile) registers long before
                # its first lease refresh lands.  Declaring it dead now
                # would permanently ban a healthy replica and
                # pointlessly drain its (empty) inbox — wait the grace
                # out first.  Members that HAVE held a lease get no
                # grace: their lapse is the real death signal.
                if now_mono - self._reg_seen.get(rid, now_mono) \
                        < self.join_grace_s:
                    continue
            # dead or drained: lease lapsed (SIGKILL, heartbeat drop,
            # clean drain exit) or publisher lost.  Drain its inbox,
            # redispatch its outstanding.
            self._dead.add(rid)
            live.discard(rid)
            if rid in draining:
                # graceful scale-down/pool-drain departure: expected,
                # not a failure — but the sweep + redispatch below
                # still runs, so even a drain that raced a final
                # dispatch loses nothing
                self._obs_drains.inc()
                log.info("router: replica %s drained and left the "
                         "fleet", rid)
            else:
                self._obs_deaths.inc()
                log.warning("router: replica %s presumed dead; "
                            "redispatching its outstanding requests", rid)
            self._sweep_dead(rid, regs)
            for k, e in entries.items():
                if k in done or e["assigned"] != rid:
                    continue
                e["assigned"] = None
                e["attempts"] += 1
                self._journal_assign(k, e)
                progressed = True
                self._obs_redispatched.inc()
                trace = e.get("trace")
                if trace is not None:
                    obs.events.record("redispatch", trace=trace.trace_id,
                                      from_replica=rid,
                                      attempts=e["attempts"])
                if e["attempts"] > self.max_redispatch:
                    req = e["req"]
                    self._journal_terminal(k, "failed", ())
                    complete(k, Completion(
                        rid=req.rid, prompt=np.asarray(req.prompt),
                        tokens=np.zeros((0,), np.int32),
                        reason="failed"))
                    self._decide("failed", e, attempts=e["attempts"])

        # 2.5) quarantine probe cycle: golden-query the quarantined
        # (still-live) replicas toward reinstatement or retirement.
        # After the death sweep on purpose — a quarantined replica that
        # DIED was just dropped and must not be probed.
        if self.quarantine is not None:
            self.quarantine.tick(live=live)
            quarantined = self.quarantine.quarantined()

        # 3) dispatch unassigned requests least-loaded
        now = self._clock()
        self._backoff = {r: t for r, t in self._backoff.items() if t > now}
        loads = self.loads(regs)
        self._update_backoffs(loads)
        unhealthy: set[str] = set()
        if self._health is not None:
            v = self._health.verdict()
            rank_to_rid = {int(info.get("rank", -1)): rid
                           for rid, info in regs.items()}
            for r in v.get("stale", []) + v.get("lost", []):
                rid = rank_to_rid.get(int(r))
                if rid is not None:
                    unhealthy.add(rid)
        candidates = [rid for rid in sorted(live)
                      if rid not in self._backoff
                      and rid not in unhealthy
                      # graceful drain: admissions steer away; in-flight
                      # work finishes before the replica stops
                      and rid not in draining
                      # integrity quarantine: alive and heartbeating,
                      # but under suspicion — probed, never dispatched
                      and rid not in quarantined
                      # blue-green: traffic is pinned to the active pool
                      and (pool is None or regs.get(rid, {})
                           .get("pool", "default") == pool)
                      # steer around a replica mid-hot-swap: it has
                      # paused admission to drain; feeding it would just
                      # park requests behind the rebind
                      and not loads.get(rid, {}).get("swapping")]
        # fleet-wide overload state: any candidate replica in degraded
        # mode puts the ROUTER in degraded mode too — new best-effort
        # dispatches get their budgets clamped at the wire.  A wired
        # alert plane contributes through the same switch: a firing
        # FleetDegraded rule (merged serve/degraded > 0 in the TSDB)
        # arms the clamp even when the advertising replica is not a
        # current candidate.
        degraded = (any(loads.get(rid, {}).get("degraded")
                        for rid in candidates)
                    or (self.alerts is not None
                        and self.alerts.is_firing("FleetDegraded")))
        self._obs_degraded.set(1.0 if degraded else 0.0)
        # two-stage scheduling: fresh (prefill-stage) requests go to
        # prefill/both replicas, handoff (decode-stage) requests to
        # decode/both — a homogeneous "both" fleet degenerates to one
        # shared pool and nothing below changes
        stage_cands = {
            "prefill": [rid for rid in candidates
                        if regs.get(rid, {}).get("role", "both")
                        in ("prefill", "both")],
            "decode": [rid for rid in candidates
                       if regs.get(rid, {}).get("role", "both")
                       in ("decode", "both")]}
        depth = {"prefill": 0, "decode": 0}
        for k2, e2 in entries.items():
            if k2 not in done and e2.get("arrived", True):
                s2 = e2.get("stage", "prefill")
                # a pulling request is still pre-prefill work
                depth["prefill" if s2 == "pull" else s2] += 1
        for stage, g in self._obs_stage_depth.items():
            g.set(depth[stage])
        if candidates:
            assigned_counts: dict[str, int] = {}
            for e in entries.values():
                if e["assigned"] is not None:
                    assigned_counts[e["assigned"]] = (
                        assigned_counts.get(e["assigned"], 0) + 1)
            wall = self._wall()
            # prefix residency summaries: one coord read per replica
            # per poll, shared by every dispatch decision below.  The
            # refresh covers ALL live replicas, not just candidates — a
            # draining or backed-off replica cannot take the request,
            # but its resident pages can still be PULLED from it.
            self.prefix_dir.refresh(sorted(live | set(candidates)))
            prefix_map = self.prefix_dir.affinity(candidates)
            # the SLO predictor: the best queue-wait any candidate
            # advertises at the configured percentile — if even that
            # replica would (probably) blow a request's deadline, no
            # assignment can save it
            best_wait = min(
                (loads.get(rid, {}).get("queue_wait_q") or 0.0
                 for rid in candidates), default=0.0)
            for k, e in entries.items():
                if (k in done or e["assigned"] is not None
                        or not e.get("arrived", True)):
                    continue
                req = e["req"]
                if req.deadline_s is not None and wall > req.deadline_s:
                    self._journal_terminal(k, "timeout", ())
                    complete(k, Completion(
                        rid=req.rid, prompt=np.asarray(req.prompt),
                        tokens=np.zeros((0,), np.int32), reason="timeout"))
                    self._decide("timeout", e, stage="router")
                    progressed = True
                    continue
                if (req.deadline_s is not None and e["attempts"] == 0
                        and e.get("stage", "prefill") == "prefill"
                        and wall + best_wait > req.deadline_s):
                    # SLO admission: shed BEFORE any replica pays a
                    # prefill.  Only ever on first dispatch — a request
                    # already prefilled once (redispatch, or a
                    # decode-stage handoff) is sunk cost and races the
                    # deadline instead.
                    self._obs_slo_shed.inc()
                    self._journal_terminal(k, "shed", ())
                    complete(k, Completion(
                        rid=req.rid, prompt=np.asarray(req.prompt),
                        tokens=np.zeros((0,), np.int32), reason="shed"))
                    self._decide("shed", e, predicted_wait_s=best_wait)
                    progressed = True
                    continue
                stage = e.get("stage", "prefill")
                if stage == "pull":
                    continue   # parked on the owner's export (step 1.5)
                owner, cov = (None, 0)
                if (stage == "prefill" and len(self.prefix_dir)
                        and not e.get("pull_tried")
                        and e.get("prefix_ref") is None):
                    owner, cov = self.prefix_dir.best_owner(
                        req.prompt, live=live)
                if (owner is not None and cov >= self.pull_min_blocks
                        and owner in stage_cands["prefill"]):
                    # the covering replica can take the request itself:
                    # content-based affinity placement beats any pull
                    # (the pages are already where the request lands)
                    rid = owner
                elif (owner is not None
                        and cov >= self.pull_min_blocks):
                    # covering replica NOT dispatchable (draining,
                    # wrong role, backed off, quarantined): park the
                    # request in the pull stage and ask the owner to
                    # export its pages — journal FIRST, so a crash
                    # mid-initiation recovers an ordinary prefill
                    e["pull_tried"] = True
                    e["stage"] = "pull"
                    e["pull_owner"] = owner
                    e["pull_deadline"] = (self._clock()
                                          + self.pull_timeout_s)
                    self._journal_pull(k, e)
                    self.client.set(
                        f"{self.ns}/pullreq/{owner}/{k}",
                        wire.encode_record("pullreq", {
                            "key": k,
                            "prompt": np.asarray(req.prompt)
                            .astype(int).tolist()}))
                    self._obs_prefix_pulls.inc()
                    progressed = True
                    trace = e.get("trace")
                    if trace is not None:
                        obs.events.record(
                            "pull_start", trace=trace.trace_id,
                            owner=owner, blocks=cov)
                    continue
                else:
                    rid = self._pick(
                        stage_cands[stage], loads, assigned_counts,
                        # prefix affinity only steers PREFILL
                        # placement: a decode-stage admission adopts
                        # migrated private pages and never reads the
                        # prefix cache
                        prefix_hash=(getattr(req, "prefix_hash", None)
                                     if stage == "prefill" else None),
                        prefix_map=prefix_map)
                if rid is None:
                    # this stage's pool is empty right now; the OTHER
                    # stage may still have capacity — keep scanning
                    continue
                trace = e.get("trace")
                send = req if trace is None else dataclasses.replace(
                    req, trace=trace)
                if (degraded and self.degrade_max_new is not None
                        and getattr(req, "priority", 0) <= 0
                        and req.max_new_tokens > self.degrade_max_new):
                    # degrade tier 1: clamp best-effort budgets at the
                    # wire while the fleet is overloaded — a short
                    # answer now beats a rejection later.  Higher
                    # priority classes keep full budgets.
                    send = dataclasses.replace(
                        send, max_new_tokens=self.degrade_max_new)
                    self._obs_degrade_clamped.inc()
                    if trace is not None:
                        obs.events.record(
                            "degrade_clamp", trace=trace.trace_id,
                            stage="router",
                            max_new=self.degrade_max_new)
                self.client.set(
                    f"{self.ns}/inbox/{rid}/{k}",
                    _encode_request(k, send,
                                    handoff_ref=e.get("handoff_ref"),
                                    prefix_ref=e.get("prefix_ref")))
                e["assigned"] = rid
                # inbox FIRST, then journal: a crash in between leaves
                # the record open-unassigned -> recovery redispatches
                # (a double-serve dedupes at done-key consumption)
                self._journal_assign(k, e)
                assigned_counts[rid] = assigned_counts.get(rid, 0) + 1
                progressed = True
                self._obs_dispatched.inc()
                if trace is not None:
                    obs.events.record("dispatch", trace=trace.trace_id,
                                      replica=rid,
                                      attempt=e["attempts"])

        # 4) hot/cold rebalancing (opt-in): after a sustained skew
        # streak, write a {ns}/migrate_req control key naming the hot
        # replica's oldest outstanding request — the replica exports it
        # as a reason="migrate" commit (consumed in step 1), and the
        # redispatch lands least-loaded.  A cooldown per request key
        # keeps one intent in flight; an intent for a request that
        # finishes first is ignored replica-side (terminal wins).
        now_reb = self._clock()
        self._migrating = {k2: t for k2, t in self._migrating.items()
                           if t > now_reb and k2 in entries}
        if self.rebalance_after_polls and len(candidates) >= 2:
            counts: dict[str, int] = {}
            for e2 in entries.values():
                if e2["assigned"] is not None:
                    counts[e2["assigned"]] = counts.get(
                        e2["assigned"], 0) + 1
            skew = self.rebalance_hot_cold(
                loads, candidates, counts,
                min_gap=self.rebalance_min_gap)
            if skew is None:
                self._skew_streak = None
            else:
                hot, cold = skew
                prev = self._skew_streak
                n = prev[1] + 1 if prev and prev[0] == hot else 1
                self._skew_streak = (hot, n)
                if n >= self.rebalance_after_polls:
                    victim = self.rebalance_victim(
                        entries, done, hot, self._migrating)
                    if victim is not None:
                        self.client.set(
                            f"{self.ns}/migrate_req/{hot}/{victim}",
                            b"1")
                        self._migrating[victim] = (
                            now_reb + self.rebalance_timeout_s)
                        self._obs_rebalances.inc()
                        self._skew_streak = None
                        log.info("router: sustained skew on %s; "
                                 "migrating %s toward %s", hot,
                                 victim, cold)
                        trace = entries[victim].get("trace")
                        if trace is not None:
                            obs.events.record(
                                "rebalance", trace=trace.trace_id,
                                from_replica=hot, to=cold)
        return progressed


    # -- blue-green structural rollout -------------------------------------

    def roll_structural(self, spawn, n: int, *, canary, expect_tokens,
                        green_pool: str = "green",
                        warmup_timeout_s: float = 180.0,
                        canary_timeout_s: float = 60.0,
                        drain_timeout_s: float = 60.0) -> dict:
        """Blue-green rollout for STRUCTURAL changes (tokenizer/config
        version bumps the in-place weight hot-swap cannot express).

        State machine::

            spawn green --> warm (n registered+heartbeating) --> canary
              (exact-check) --> COMMIT (shift {ns}/pool, drain blue)
            any warmup/canary failure --> ROLLBACK (stop green, blue
              keeps serving untouched)

        Args:
          spawn: zero-arg callable launching the green replicas (e.g. a
            :func:`scale_fleet` closure with the new model args and
            ``--pool green``) and returning their ``Popen``\\ s.
          n: green replicas to wait for before the canary runs.
          canary: the probe :class:`~tpudist.models.serving.Request`.
          expect_tokens: the EXACT token sequence the green pool must
            produce for the canary (computed against a reference loop
            under the new config) — a warmed, heartbeating pool serving
            WRONG output is precisely the failure health checks miss.

        Returns a dict: ``ok``, ``stage`` (``done`` | the stage that
        failed), ``green``/``blue`` rid lists, ``procs`` (the green
        workers — the CALLER reaps them; on rollback they are already
        stopped), and ``blue_drained`` on commit.

        Composes with death detection: a BLUE replica dying mid-rollout
        is redispatched by the normal poll machinery (this method never
        touches blue until commit), and a GREEN death during
        warmup/canary triggers rollback, with blue traffic never
        shifted."""
        regs0 = self.replicas()
        blue = sorted(rid for rid, info in regs0.items()
                      if info.get("pool", "default") != green_pool)
        blue_pool = (regs0[blue[0]].get("pool", "default") if blue
                     else "default")
        procs = list(spawn())

        def rollback(stage: str, why: str) -> dict:
            self._obs_rollbacks.inc()
            log.warning("roll_structural: %s failed (%s); rolling back "
                        "to pool %r", stage, why, blue_pool)
            regs = self.replicas()
            green_now = sorted(rid for rid, info in regs.items()
                               if info.get("pool", "default") == green_pool)
            for rid in green_now:
                try:
                    self.client.set(f"{self.ns}/stop/{rid}", b"1")
                except ConnectionError:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=15.0)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
            for rid in green_now:
                self._sweep_dead(rid, regs)
                try:
                    self.client.delete(f"{self.ns}/stop/{rid}")
                except ConnectionError:
                    pass
            return {"ok": False, "stage": stage, "reason": why,
                    "green": green_now, "blue": blue, "procs": procs}

        # -- warm: n green replicas registered AND heartbeating
        deadline = time.monotonic() + warmup_timeout_s
        green: list[str] = []
        while True:
            live = self.live()
            green = sorted(rid for rid, info in self.replicas().items()
                           if info.get("pool", "default") == green_pool
                           and rid in live)
            if len(green) >= n:
                break
            exited = [(p.pid, p.returncode) for p in procs
                      if p.poll() is not None]
            if exited:
                return rollback("warmup",
                                f"green worker(s) died: {exited}")
            if time.monotonic() > deadline:
                return rollback("warmup", f"only {len(green)} of {n} "
                                f"green replicas live after "
                                f"{warmup_timeout_s:.0f}s")
            time.sleep(0.1)

        # -- canary: one probe request, exact-checked.  The key's
        # "canary" prefix keeps it out of the request sequence space
        # (and is what the CANARY_CORRUPT injection targets).
        key = f"canary-{self._seq:08d}"
        self._seq += 1
        target = green[0]
        self.client.set(f"{self.ns}/inbox/{target}/{key}",
                        _encode_request(key, canary))
        deadline = time.monotonic() + canary_timeout_s
        tokens = None
        while time.monotonic() < deadline:
            try:
                raw = self.client.get(f"{self.ns}/done/{key}")
            except ConnectionError:
                raw = None
            if raw is not None:
                self.client.delete(f"{self.ns}/done/{key}")
                try:
                    doc = wire.decode_record(
                        raw, expect="completion", namespace=self.ns,
                        key=key, replica=target)
                except wire.WireError as err:
                    return rollback(
                        "canary",
                        f"undecodable canary answer ({err.reason})")
                tokens = np.asarray(doc.get("tokens", ()), np.int32)
                break
            if any(p.poll() is not None for p in procs):
                return rollback("canary", "green worker died mid-canary")
            time.sleep(0.05)
        if tokens is None:
            return rollback("canary", "canary timed out")
        expect = np.asarray(expect_tokens, np.int32)
        if not np.array_equal(tokens, expect):
            return rollback(
                "canary", f"output mismatch (got {tokens.tolist()}, "
                f"expected {expect.tolist()})")

        # -- commit: shift traffic, then drain blue gracefully (steer
        # admissions away, let in-flight finish, stop, wait out the
        # lease) — zero requests lost on either side of the cut
        self.client.set(f"{self.ns}/pool", green_pool.encode())
        self._obs_rolls.inc()
        log.info("roll_structural: canary exact-matched; pool shifted "
                 "to %r, draining blue %s", green_pool, blue)
        blue_drained = drain_replicas(self.client, blue,
                                      namespace=self.ns,
                                      timeout_s=drain_timeout_s)
        return {"ok": True, "stage": "done", "green": green,
                "blue": blue, "procs": procs,
                "blue_drained": blue_drained}


# -- fleet process helpers (tests, bench, example, CI) ---------------------

def build_tiny_lm(vocab: int = 64, layers: int = 2, heads: int = 4,
                  kv_heads: int = 2, embed: int = 64, seq_len: int = 96,
                  seed: int = 0):
    """The fleet's shared tiny model: every replica (and the reference
    single loop) builds IDENTICAL weights from the same seed, which is
    what makes redispatched greedy output exact-match verifiable."""
    import jax
    import jax.numpy as jnp

    from tpudist.models.transformer import TransformerConfig, TransformerLM

    cfg = TransformerConfig(vocab_size=vocab, num_layers=layers,
                            num_heads=heads, num_kv_heads=kv_heads,
                            embed_dim=embed, max_seq_len=seq_len)
    params = TransformerLM(cfg).init(
        jax.random.key(seed), jnp.zeros((1, 2), jnp.int32))["params"]
    return cfg, params


def request_drain(client: CoordClient, rids: Sequence[str], *,
                  namespace: str = DEFAULT_NAMESPACE) -> None:
    """Mark replicas for graceful drain (``{ns}/draining/{rid}``): the
    router steers new admissions away immediately; the replicas keep
    working their queues.  Follow with :func:`drain_replicas` (or the
    autoscaler's poll loop) to stop them once empty."""
    for rid in rids:
        client.set(f"{namespace}/draining/{rid}", b"1")


def drain_replicas(client: CoordClient, rids: Sequence[str], *,
                   namespace: str = DEFAULT_NAMESPACE,
                   timeout_s: float = 60.0,
                   poll_s: float = 0.1) -> bool:
    """Gracefully drain replicas to a stop, never losing a request:
    mark them draining (router admissions steer away), wait for each
    inbox to empty (everything dispatched has been picked up), set the
    targeted stop key (the worker's close path finishes its queued and
    in-flight work, commits every completion, then exits cleanly), and
    wait the heartbeat lease out.  Returns True when every replica is
    gone within ``timeout_s`` (residual coordination keys are cleaned
    up), False on timeout (drain keys left in place — the replicas are
    still steered away from, just not yet stopped)."""
    request_drain(client, rids, namespace=namespace)
    regs: dict[str, dict] = {}
    prefix = f"{namespace}/replica/"
    for rid in rids:
        raw = client.get(f"{prefix}{rid}")
        if raw is not None:
            regs[rid] = json.loads(raw.decode())
    mark = f"{namespace}:"
    deadline = time.monotonic() + timeout_s
    stopped: set[str] = set()
    while True:
        live = {n[len(mark):] for n in client.live()
                if n.startswith(mark)}
        remaining = [rid for rid in rids
                     if rid in live or rid not in stopped]
        for rid in remaining:
            if rid not in live:
                stopped.add(rid)   # already gone (death beat the drain)
            elif (rid not in stopped
                    and not client.keys(f"{namespace}/inbox/{rid}/")):
                client.set(f"{namespace}/stop/{rid}", b"1")
                stopped.add(rid)
        if not [rid for rid in rids
                if rid in live or rid not in stopped]:
            for rid in rids:   # residue: this drain owns the cleanup
                for key in (f"{namespace}/draining/{rid}",
                            f"{namespace}/stop/{rid}",
                            f"{namespace}/replica/{rid}",
                            f"{namespace}/metrics/"
                            f"{regs.get(rid, {}).get('rank')}"):
                    try:
                        client.delete(key)
                    except ConnectionError:
                        pass
            return True
        if time.monotonic() > deadline:
            return False
        time.sleep(poll_s)


def alloc_replica_indices(client: CoordClient, n: int, *,
                          namespace: str = DEFAULT_NAMESPACE) -> list[int]:
    """Reserve ``n`` fresh replica indices from the fleet's add-chain
    (``{ns}/replica_index``).  The coord ``add`` is atomic, so an
    operator-initiated and an autoscaler-initiated scale-up racing each
    other get DISJOINT index ranges — two replicas can never collide on
    a registration key, rank, or metrics slot."""
    end = int(client.add(f"{namespace}/replica_index", int(n)))
    return list(range(end - int(n), end))


def _seed_replica_index(client: CoordClient, upto: int, *,
                        namespace: str = DEFAULT_NAMESPACE) -> None:
    """Advance the index chain to at least ``upto`` (covers indices an
    explicit ``start_index`` caller placed outside the chain)."""
    cur = int(client.add(f"{namespace}/replica_index", 0))
    if cur < upto:
        client.add(f"{namespace}/replica_index", upto - cur)


def _spawn_replica(coord_addr: str, index: int, *,
                   namespace: str,
                   replica_args: Sequence[str] = (),
                   env_extra: dict | None = None,
                   platform: str = "cpu") -> subprocess.Popen:
    """One replica worker subprocess (``replica-id r{index}``, rank
    ``index``) — the shared spawn body of :func:`launch_local_fleet`
    and :func:`scale_fleet`."""
    host, port = coord_addr.rsplit(":", 1)
    pkg_root = str(Path(__file__).resolve().parents[2])
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [pkg_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH")
                      else []))
    env.setdefault("JAX_PLATFORMS", platform)
    env.update({k: str(v) for k, v in (env_extra or {}).items()})
    return subprocess.Popen(
        [sys.executable, "-m", "tpudist.runtime.router",
         "--coord", f"{host}:{port}", "--replica-id", f"r{index}",
         "--rank", str(index), "--namespace", namespace,
         *replica_args],
        env=env)


def launch_local_fleet(coord_addr: str, n: int, *,
                       namespace: str = DEFAULT_NAMESPACE,
                       replica_args: Sequence[str] = (),
                       env_overrides: dict[int, dict] | None = None,
                       platform: str = "cpu") -> list[subprocess.Popen]:
    """Spawn ``n`` replica worker subprocesses on this host (tests,
    bench, CI, the example).  ``env_overrides[i]`` adds env vars to
    replica ``i`` — the fault-injection knobs go in this way, so a kill
    schedule hits exactly the replica the scenario names.  Also seeds
    the fleet's replica-index add-chain past ``n`` so later
    :func:`scale_fleet` calls allocate collision-free indices."""
    try:
        host, port = coord_addr.rsplit(":", 1)
        _seed_replica_index(CoordClient(host, int(port)), n,
                            namespace=namespace)
    except (ConnectionError, OSError):
        pass   # chain seeds lazily on the first scale-up instead
    return [_spawn_replica(coord_addr, i, namespace=namespace,
                           replica_args=replica_args,
                           env_extra=(env_overrides or {}).get(i),
                           platform=platform)
            for i in range(n)]


def scale_fleet(coord_addr: str, n: int, *,
                start_index: int | None = None,
                namespace: str = DEFAULT_NAMESPACE,
                replica_args: Sequence[str] = (),
                env_overrides: dict[int, dict] | None = None,
                env_extra: dict | None = None,
                platform: str = "cpu") -> list[subprocess.Popen]:
    """Scale a RUNNING fleet up by ``n`` joiner replicas.  Joiners
    register against the live coordination planes and the router admits
    them on its next poll; pass the same ``--snapshot-dir`` the fleet
    was launched with so a joiner restores the CURRENT weights (keeping
    greedy output exact-match with the incumbents).

    Indices (ids ``r{i}``, ranks to match — ranks key the metrics
    namespace, so they must never collide with existing members, dead
    ones included) are allocated from the fleet's atomic add-chain by
    default (:func:`alloc_replica_indices`), so two scale-ups racing
    each other — an operator and the autoscaler, say — get disjoint
    ranges.  An explicit ``start_index`` keeps the legacy caller-picked
    layout and advances the chain past it.

    ``env_overrides`` is keyed by absolute index (explicit
    ``start_index`` callers); ``env_extra`` applies to every joiner
    (chain-allocated callers don't know indices up front).  Each
    returned ``Popen`` carries its ``replica_index`` attribute."""
    host, port = coord_addr.rsplit(":", 1)
    if start_index is None:
        indices = alloc_replica_indices(CoordClient(host, int(port)), n,
                                        namespace=namespace)
    else:
        indices = list(range(start_index, start_index + n))
        try:
            _seed_replica_index(CoordClient(host, int(port)),
                                start_index + n, namespace=namespace)
        except (ConnectionError, OSError):
            pass
    procs = []
    for i in indices:
        extra = dict(env_extra or {})
        extra.update((env_overrides or {}).get(i) or {})
        p = _spawn_replica(coord_addr, i, namespace=namespace,
                           replica_args=replica_args,
                           env_extra=extra, platform=platform)
        p.replica_index = i
        procs.append(p)
    return procs


def stop_fleet(client: CoordClient, procs: Sequence[subprocess.Popen], *,
               namespace: str = DEFAULT_NAMESPACE,
               timeout_s: float = 30.0) -> list[int]:
    """Set the fleet-wide stop key, reap every worker, and return their
    exit codes (in ``procs`` order).  Unexpected terminations are
    SURFACED, not raised: a fault scenario's SIGKILLed replica is a
    legitimate nonzero exit the caller asserts on, so this logs a
    warning per casualty and leaves the verdict to the caller."""
    try:
        client.set(f"{namespace}/stop", b"1")
    except ConnectionError:
        pass
    deadline = time.monotonic() + timeout_s
    codes: list[int] = []
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            log.warning("stop_fleet: pid %d ignored the stop key for "
                        "%.0fs; killing it", p.pid, timeout_s)
            p.kill()
            p.wait()
        codes.append(p.returncode)
        if p.returncode != 0:
            log.warning("stop_fleet: pid %d exited with %d "
                        "(negative = killed by that signal)",
                        p.pid, p.returncode)
    return codes


def wait_live(client: CoordClient, n: int, *,
              namespace: str = DEFAULT_NAMESPACE,
              timeout_s: float = 60.0,
              procs: Sequence[subprocess.Popen] | None = None) -> set[str]:
    """Block until ``n`` replicas hold heartbeat leases (fleet warm-up:
    replica startup is jax import + model compile, and routing before
    the fleet assembles concentrates all early requests on whichever
    replica won the race).  Returns the live replica-id set.

    Pass ``procs`` to FAIL FAST: a worker that exits before the fleet
    assembles (bad args, import error) raises ``RuntimeError`` with its
    exit code immediately instead of burning the whole timeout.  Either
    way, the timeout diagnostic lists replicas that REGISTERED but hold
    no lease — the registered-then-died shape that otherwise reads as
    a silent hang."""
    mark = f"{namespace}:"
    deadline = time.monotonic() + timeout_s

    def registered_not_live(live: set[str]) -> list[str]:
        try:
            prefix = f"{namespace}/replica/"
            regs = {k[len(prefix):] for k in client.keys(prefix)}
        except ConnectionError:
            return []
        return sorted(regs - live)

    while True:
        live = {name[len(mark):] for name in client.live()
                if name.startswith(mark)}
        if len(live) >= n:
            return live
        if procs is not None:
            exited = [(p.pid, p.returncode) for p in procs
                      if p.poll() is not None]
            # a proc that exited may legitimately belong to an earlier,
            # already-finished scenario only if the caller passed it;
            # here any exit before assembly is fatal to the wait
            if exited:
                raise RuntimeError(
                    f"fleet: worker(s) died before {n} replicas went "
                    f"live: {[f'pid {pid} -> exit {rc}' for pid, rc in exited]} "
                    f"(live: {sorted(live)}, registered-but-dead: "
                    f"{registered_not_live(live)})")
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"fleet: only {sorted(live)} of {n} replicas live "
                f"after {timeout_s:.0f}s (registered-but-dead: "
                f"{registered_not_live(live)})")
        time.sleep(0.1)


def roll_weights(client: CoordClient, snapshot_dir: str | os.PathLike,
                 params, *, version: int,
                 namespace: str = DEFAULT_NAMESPACE,
                 meta: dict | None = None) -> None:
    """Publish a new weight version to a running fleet: persist
    ``params`` to the shared snapshot dir (synchronously — DURABILITY
    FIRST: no replica may learn of a version whose bytes are not yet
    committed on disk), then bump ``{ns}/weights/version``, which every
    replica's source poll watches.  The fleet then rolls one replica at
    a time (the ticket chain in the module docstring); follow with
    :func:`wait_swapped` to block until the roll completes."""
    from tpudist.elastic.checkpoint import Checkpointer

    m = {"version": int(version)}
    if meta:
        m.update(meta)
    Checkpointer(snapshot_dir, layout="steps").save(
        int(version), params, meta=m)
    client.set(f"{namespace}/weights/version",
               str(int(version)).encode())


def wait_swapped(client: CoordClient, n: int, version: int, *,
                 namespace: str = DEFAULT_NAMESPACE,
                 timeout_s: float = 60.0) -> set[int]:
    """Block until ``n`` replicas publish ``serve/weights_version >=
    version`` (the gauge each one bumps when its drain-gated rebind
    lands).  Returns the swapped RANK set."""
    deadline = time.monotonic() + timeout_s
    while True:
        swapped: set[int] = set()
        try:
            snaps = collect(client, f"{namespace}/metrics")
        except ConnectionError:
            snaps = {}
        for rank, snap in snaps.items():
            v = (snap.get("gauges", {}).get("serve/weights_version")
                 or {}).get("value")
            if v is not None and v >= version:
                swapped.add(rank)
        if len(swapped) >= n:
            return swapped
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"fleet: only ranks {sorted(swapped)} of {n} replicas "
                f"reached weights version {version} after "
                f"{timeout_s:.0f}s")
        time.sleep(0.1)


def exit_reports(client: CoordClient, *,
                 namespace: str = DEFAULT_NAMESPACE) -> dict[str, dict]:
    """Clean-exit reports by replica id (a SIGKILLed replica leaves
    none — that absence is itself the assertion)."""
    out = {}
    prefix = f"{namespace}/exit/"
    for key in client.keys(prefix):
        raw = client.get(key)
        if raw is None:
            continue
        try:
            out[key[len(prefix):]] = wire.decode_record(
                raw, expect="heartbeat", namespace=namespace,
                key=key[len(prefix):])
        except wire.WireError as e:
            log.warning("exit_reports: undecodable report %s (%s)",
                        key, e.reason)
    return out


# -- replica / router CLI --------------------------------------------------

def _run_route_mode(args) -> None:  # pragma: no cover - subprocess entry
    """``--route``: drive a Router over an existing fleet from a
    requests file, streaming one JSONL result line per completion
    (append + flush, so a SIGKILLed router's partial output survives).
    ``--recover`` rebuilds from the journal instead of submitting: the
    results file read-back tells the recovered router which terminals
    the crashed one already delivered — the failover path of the
    module docstring, end to end."""
    from tpudist.models.serving import Request

    host, port = args.coord.rsplit(":", 1)
    client = CoordClient(host, int(port))
    router = Router(client, namespace=args.namespace,
                    poll_s=args.poll_s,
                    lost_after_s=args.lost_after)
    results = Path(args.results)

    def deliver(key: str, comp) -> None:
        with results.open("a") as fh:
            fh.write(json.dumps({
                "rid": str(comp.rid),
                "tokens": np.asarray(comp.tokens).astype(int).tolist(),
                "reason": comp.reason}) + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    if args.recover:
        delivered = []
        if results.exists():
            for line in results.read_text().splitlines():
                if line.strip():
                    delivered.append(str(json.loads(line)["rid"]))
        comps = router.recover(timeout_s=args.timeout,
                               delivered=delivered,
                               on_complete=deliver)
    else:
        docs = json.loads(Path(args.requests).read_text())
        reqs = [Request(prompt=np.asarray(d["prompt"], np.int32),
                        max_new_tokens=int(d["max_new_tokens"]),
                        rid=str(d["rid"]),
                        deadline_s=d.get("deadline_s"),
                        priority=int(d.get("priority", 0)))
                for d in docs]
        comps = router.run(reqs, timeout_s=args.timeout,
                           on_complete=deliver)
    log.info("router: %s finished %d completions",
             "recovery" if args.recover else "route", len(comps))


def main() -> None:  # pragma: no cover - subprocess entry point
    """Run one serve replica: ``python -m tpudist.runtime.router --coord
    HOST:PORT --replica-id r0 --rank 0 [model/serve args]`` — or, with
    ``--route``, the ROUTER side from a requests file (``--recover``
    resumes a crashed router from its journal).

    Builds the deterministic tiny LM (same ``--seed`` across the fleet
    => identical weights => redispatch exact-match) and serves until a
    stop key appears.  The fault-injection env (``TPUDIST_FAULT_*``) is
    read by the hooks already threaded through CoordClient/ServeLoop —
    nothing to wire here."""
    import argparse

    ap = argparse.ArgumentParser(
        description="tpudist serve replica / router")
    ap.add_argument("--coord", required=True, help="coord server host:port")
    ap.add_argument("--route", action="store_true",
                    help="run the ROUTER side instead of a replica")
    ap.add_argument("--recover", action="store_true",
                    help="with --route: rebuild from {ns}/journal/* "
                         "instead of submitting --requests")
    ap.add_argument("--requests", default="",
                    help="route mode: JSON file of request docs "
                         "(rid, prompt, max_new_tokens, ...)")
    ap.add_argument("--results", default="",
                    help="route mode: JSONL file results append to")
    ap.add_argument("--poll-s", type=float, default=0.02)
    ap.add_argument("--lost-after", type=float, default=5.0)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--replica-id", default=None)
    ap.add_argument("--rank", type=int, default=None)
    ap.add_argument("--namespace", default=DEFAULT_NAMESPACE)
    ap.add_argument("--ttl", type=float, default=2.0)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--embed", type=int, default=64)
    ap.add_argument("--seq-len", type=int, default=96)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--steps-per-sync", type=int, default=4)
    ap.add_argument("--prefill-chunk", type=int, default=8)
    ap.add_argument("--stop-tokens", default="",
                    help="comma-separated stop token ids")
    ap.add_argument("--cache-layout", default="dense",
                    choices=["dense", "paged"])
    ap.add_argument("--kv-block-size", type=int, default=16)
    ap.add_argument("--kv-num-blocks", type=int, default=0,
                    help="0 = dense-capacity default")
    ap.add_argument("--max-queue", type=int, default=-1,
                    help="-1 = unbounded")
    ap.add_argument("--degrade-queue", type=int, default=-1,
                    help="soft overload watermark (-1 = max-queue/2 "
                         "when max-queue is set)")
    ap.add_argument("--degrade-max-new", type=int, default=32,
                    help="degraded-mode max_new_tokens clamp for "
                         "priority-0 traffic")
    ap.add_argument("--pool", default="default",
                    help="blue-green pool tag; the router only "
                         "dispatches to the active pool")
    ap.add_argument("--role", default="both",
                    choices=["both", "prefill", "decode"],
                    help="disaggregated serving role: 'prefill' runs "
                         "chunked prefill to completion and hands the "
                         "KV off; 'decode' adopts migrated KV and "
                         "decodes; 'both' (default) is a unified "
                         "replica (requires --cache-layout paged for "
                         "prefill/decode)")
    ap.add_argument("--preempt", default="degrade",
                    choices=["degrade", "migrate"],
                    help="overload policy: 'degrade' clamps best-effort "
                         "budgets; 'migrate' pauses the lowest-priority "
                         "in-flight decode via KV-page export instead "
                         "(requires --cache-layout paged) and enables "
                         "fast drain + rebalance intents")
    ap.add_argument("--snapshot-dir", default="",
                    help="fleet weight snapshot dir (Checkpointer, "
                         "layout=steps): restored at startup (joiners "
                         "pick up the fleet's current weights) and on "
                         "every weights/version bump (rolling hot-swap)")
    ap.add_argument("--swap-turn-timeout", type=float, default=10.0,
                    help="seconds to wait on earlier swap tickets "
                         "before proceeding anyway (dead-holder "
                         "liveness fallback)")
    args = ap.parse_args()

    if args.route or args.recover:
        if not args.results:
            ap.error("--route/--recover require --results")
        if not args.recover and not args.requests:
            ap.error("--route requires --requests")
        _run_route_mode(args)
        return
    if args.replica_id is None or args.rank is None:
        ap.error("replica mode requires --replica-id and --rank")

    from tpudist.models.serving import ServeLoop

    cfg, params = build_tiny_lm(args.vocab, args.layers, args.heads,
                                args.kv_heads, args.embed, args.seq_len,
                                args.seed)
    stop = ([int(t) for t in args.stop_tokens.split(",") if t.strip()]
            or None)
    loop = ServeLoop(
        cfg, params, num_slots=args.slots,
        steps_per_sync=args.steps_per_sync,
        prefill_chunk=args.prefill_chunk, stop_tokens=stop,
        cache_layout=args.cache_layout,
        kv_block_size=args.kv_block_size,
        kv_num_blocks=args.kv_num_blocks or None,
        max_queue=None if args.max_queue < 0 else args.max_queue,
        degrade_queue=None if args.degrade_queue < 0
        else args.degrade_queue,
        degrade_max_new=args.degrade_max_new,
        role=args.role, preempt=args.preempt)
    host, port = args.coord.rsplit(":", 1)
    client = CoordClient(host, int(port))
    worker = ReplicaWorker(loop, client, args.replica_id,
                           rank=args.rank, namespace=args.namespace,
                           ttl_s=args.ttl,
                           snapshot_dir=args.snapshot_dir or None,
                           swap_turn_timeout_s=args.swap_turn_timeout,
                           pool=args.pool)
    log.info("replica %s (rank %d) serving on %s", args.replica_id,
             args.rank, args.namespace)
    worker.serve()


if __name__ == "__main__":  # pragma: no cover
    main()
