"""CPU-simulated device meshes — the single place that knows the dance.

The TPU-native analog of the reference's ``mp.spawn``-on-localhost pattern
(`model_parallel_ResNet50.py:260`, SURVEY.md §4): N fake CPU devices let
mesh/sharding/elastic code run anywhere.  Forcing is belt-and-braces because
ambient environments may register a real TPU backend at startup AND pin
``jax_platforms`` via ``jax.config`` (which overrides the env var): we set
the env vars (read at backend initialization) and update the config after
import.  Must be called before anything initializes a JAX backend.
"""

from __future__ import annotations

import os


def force_cpu_devices(n: int = 8, check: bool = True) -> None:
    """Force the CPU platform with ``n`` simulated devices.

    ``check=False`` skips the device-count probe — required in processes
    that will call ``jax.distributed.initialize`` afterwards (the probe
    itself initializes the XLA backend, which must not happen first).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if check and jax.device_count() < n:
        raise RuntimeError(
            f"requested {n} simulated devices but the backend was already "
            f"initialized with {jax.device_count()}; call force_cpu_devices "
            "before any jax device query (XLA_FLAGS is read only once)"
        )
