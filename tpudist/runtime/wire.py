"""Checksummed wire format for coord KV payloads.

The fleet's coordination traffic (requests, completions, journal
records, probe/exit reports) crosses process and machine boundaries as
raw bytes in the coord KV store.  PRs 6-12 made the fleet robust to
payloads that *vanish* (dead replicas, killed routers, brownouts); this
module is the defense against payloads that *arrive wrong* — a
bit-flipped buffer from flaky HBM, a truncated write, a replica
speaking a schema the router does not.

Every framed payload is::

    b"TPW1" | crc32c (4 bytes, big-endian) | kind tag (1 byte) | body

where the checksum covers the kind tag AND the JSON body, so a flip
anywhere past the magic is caught at decode.  The magic prefix keeps
framed and legacy payloads distinguishable: :func:`decode_record`
accepts UNFRAMED plain-JSON payloads (pre-integrity writers, the
offline simulator's fakes, tests that plant done keys by hand) without
a checksum — integrity is opt-in per writer, never a flag-day.

Checksum choice: CRC32C (Castagnoli), the polynomial storage and
transport stacks (iSCSI, ext4, gRPC) standardized on for exactly this
silent-corruption class — and computable with a 256-entry table in
pure Python, because the container may not grow new dependencies.
This is an INTEGRITY check, not authentication: it catches flipped
bits, not an adversary (who could recompute it).

Decode failures raise :class:`WireError` — one typed error carrying
``reason`` (``checksum`` / ``truncated`` / ``schema`` / ``json`` /
``kind``) plus the namespace/key/replica attribution the router needs
to count the mismatch against the offending replica and redispatch the
request instead of crashing the poll loop (or worse, delivering the
corruption).
"""

from __future__ import annotations

import json
import struct

__all__ = ["WIRE_MAGIC", "WIRE_KINDS", "WireError", "crc32c",
           "encode_record", "decode_record"]

WIRE_MAGIC = b"TPW1"

# kind -> tag byte.  The tag is covered by the checksum, so a flipped
# tag surfaces as reason="checksum", not as a bogus schema verdict.
WIRE_KINDS = {
    "request": 1,      # router -> replica inbox dispatch (golden
    #                    probes ride this kind too: to a replica a
    #                    probe IS a request)
    "completion": 2,   # replica -> router done commit
    "journal": 3,      # router crash-recovery lifecycle record
    "heartbeat": 4,    # liveness/exit report payloads
    "prefix": 5,       # replica -> router prefix-cache affinity summary
    "kv_migration": 6,  # prefill replica -> decode replica KV handoff
    #                     (ordered pages + lengths + prefix-hash chain;
    #                     see tpudist.runtime.disagg)
    "pullreq": 7,      # router -> owner replica: export a prefix run
    #                    from HBM/host tier for a peer (pull-mode KV)
    "pulldone": 8,     # owner replica -> router: export finished, the
    #                    payload's transport ref (or null on a miss)
}
_TAG_TO_KIND = {tag: kind for kind, tag in WIRE_KINDS.items()}

_HEADER = struct.Struct(">4sIB")   # magic, crc32c, kind tag


class WireError(RuntimeError):
    """A coord KV payload that failed integrity verification.

    Attributes:
      reason: ``checksum`` (crc mismatch), ``truncated`` (shorter than
        its header), ``schema`` (unknown kind tag), ``json`` (body or
        legacy payload is not valid JSON), or ``kind`` (valid frame of
        the wrong record type for this decode site).
      kind: the record kind, when the frame was readable enough to know.
      namespace / key / replica: attribution filled in by the decode
        site so the router can count the strike against the replica
        that produced the bytes.
    """

    def __init__(self, reason: str, *, kind: str | None = None,
                 namespace: str = "", key: str = "",
                 replica: str = "") -> None:
        self.reason = str(reason)
        self.kind = kind
        self.namespace = str(namespace)
        self.key = str(key)
        self.replica = str(replica)
        where = "/".join(p for p in (self.namespace, self.key) if p)
        who = f" from replica {self.replica}" if self.replica else ""
        super().__init__(
            f"wire integrity failure ({self.reason})"
            f"{f' decoding {where}' if where else ''}{who}")


# -- crc32c
# (Castagnoli, reflected 0x82F63B78; table-driven, stdlib-only) -----

_CRC32C_POLY = 0x82F63B78


def _build_table() -> tuple[int, ...]:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_CRC32C_TABLE = _build_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C of ``data``; pass a previous result as ``crc`` to
    checksum a stream incrementally."""
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- framing ---------------------------------------------------------------

def encode_record(kind: str, doc: dict) -> bytes:
    """Frame ``doc`` as a checksummed ``kind`` payload."""
    tag = WIRE_KINDS.get(kind)
    if tag is None:
        raise ValueError(f"unknown wire record kind {kind!r} "
                         f"(known: {sorted(WIRE_KINDS)})")
    body = bytes([tag]) + json.dumps(doc).encode()
    return WIRE_MAGIC + struct.pack(">I", crc32c(body)) + body


def decode_record(payload: bytes, *, expect: str | None = None,
                  namespace: str = "", key: str = "",
                  replica: str = "") -> dict:
    """Verify and decode one payload; returns the JSON document.

    Framed payloads (magic prefix) are checksum-verified and, when
    ``expect`` is given, kind-checked.  Unframed payloads fall back to
    plain JSON — the pre-integrity wire — with no checksum to verify
    (``expect`` is not enforced either: legacy writers carry no kind).
    Any failure raises :class:`WireError` carrying the attribution
    kwargs verbatim.
    """
    def err(reason: str, kind: str | None = None) -> WireError:
        return WireError(reason, kind=kind, namespace=namespace,
                         key=key, replica=replica)

    if not payload.startswith(WIRE_MAGIC):
        try:
            doc = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            raise err("json") from None
        if not isinstance(doc, dict):
            raise err("json")
        return doc
    if len(payload) < _HEADER.size:
        raise err("truncated")
    _, want_crc, tag = _HEADER.unpack_from(payload)
    body = payload[len(WIRE_MAGIC) + 4:]
    if crc32c(body) != want_crc:
        raise err("checksum")
    kind = _TAG_TO_KIND.get(tag)
    if kind is None:
        # unreachable via corruption (the tag is checksummed) — this is
        # a WRITER from a future schema this reader does not know
        raise err("schema")
    if expect is not None and kind != expect:
        raise err("kind", kind=kind)
    try:
        doc = json.loads(body[1:].decode())
    except (ValueError, UnicodeDecodeError):
        raise err("json", kind=kind) from None
    if not isinstance(doc, dict):
        raise err("json", kind=kind)
    return doc
