"""tpudist.sim — trace-replay load harness + offline fleet simulator.

Scenario diversity as a regression suite (see docs/OBSERVABILITY.md):

* :mod:`tpudist.sim.scenario` — declarative :class:`ScenarioSpec`\\ s
  (arrival process, prompt/budget/deadline distributions, tenant mix,
  fleet + autoscaler policy) with per-scenario SLO
  :class:`Envelope`\\ s, plus the named ``BUILTIN`` matrix CI runs.
* :mod:`tpudist.sim.workload` — :func:`synthesize` draws a
  deterministic timed workload from a spec;
  :func:`workload_from_trace` reconstructs one from a recorded
  ``tpudist.events/1`` document, so an incident replays as a scenario.
* :mod:`tpudist.sim.simulator` — :class:`FleetSim` runs the REAL
  router + autoscaler code against a virtual clock and simulated
  replicas, emitting the same decision counters and bench-JSONL
  summary schema as a live run, orders of magnitude faster.
* :mod:`tpudist.sim.envelope` — the shared envelope checker the CI
  scenario-matrix job gates on (``python -m tpudist.sim.envelope``).

``python -m tpudist.sim --all --check`` runs the builtin matrix
offline and exits nonzero on any envelope violation.
"""

from tpudist.sim.scenario import BUILTIN, Envelope, ScenarioSpec, builtin
from tpudist.sim.workload import (
    WorkItem,
    Workload,
    service_rates_from_trace,
    synthesize,
    workload_from_trace,
)

__all__ = [
    "BUILTIN",
    "Envelope",
    "FleetSim",
    "ScenarioSpec",
    "SimFabric",
    "SimReplica",
    "VirtualClock",
    "WorkItem",
    "Workload",
    "builtin",
    "service_rates_from_trace",
    "synthesize",
    "workload_from_trace",
]


def __getattr__(name):
    # FleetSim pulls in the runtime stack (router/autoscaler -> jax);
    # keep `import tpudist.sim` light so the envelope checker and spec
    # parsing work in minimal CI environments
    if name in ("FleetSim", "SimReplica", "VirtualClock"):
        from tpudist.sim import simulator
        return getattr(simulator, name)
    if name == "SimFabric":
        from tpudist.sim.fabric import SimFabric
        return SimFabric
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
