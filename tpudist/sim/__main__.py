"""CLI: run scenarios through the offline fleet simulator.

Examples::

    # one scenario, summary row to stdout
    python -m tpudist.sim --scenario flash_crowd

    # the whole builtin matrix, envelope-gated (CI's scenario job)
    python -m tpudist.sim --all --check --jsonl SCENARIOS.jsonl

    # a spec file of your own
    python -m tpudist.sim --spec my_scenario.json --check

    # replay a recorded tpudist.events/1 trace through the simulator
    python -m tpudist.sim --replay trace.json

Rows are bench-schema JSONL (``metric``/``value``/``unit`` first, the
scenario summary as extra keys) — the same schema a live run emits, so
:mod:`tpudist.sim.envelope` gates both identically.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpudist.obs.export import jsonl_line
from tpudist.sim.scenario import ScenarioSpec, builtin, names


def _row_line(row: dict) -> str:
    extra = {k: v for k, v in row.items() if k != "completed_ok"}
    return jsonl_line(f"scenario/{row['scenario']}", row["completed_ok"],
                      "reqs", None, **extra)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Offline fleet simulator: run scenarios against the "
                    "real router/autoscaler code on a virtual clock")
    ap.add_argument("--scenario", action="append", default=[],
                    metavar="NAME",
                    help=f"builtin scenario (repeatable); one of: "
                         f"{', '.join(names())}")
    ap.add_argument("--all", action="store_true",
                    help="run every builtin scenario")
    ap.add_argument("--spec", action="append", default=[],
                    metavar="FILE.json",
                    help="scenario spec file (repeatable)")
    ap.add_argument("--replay", metavar="TRACE.json",
                    help="replay a recorded tpudist.events/1 document")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero on any envelope violation")
    ap.add_argument("--jsonl", metavar="PATH",
                    help="also append the rows to this file")
    args = ap.parse_args(argv)

    from tpudist.sim.simulator import FleetSim

    specs: list[ScenarioSpec] = []
    for name in (names() if args.all else args.scenario):
        specs.append(builtin(name))
    for path in args.spec:
        specs.append(ScenarioSpec.from_json(path))
    if not specs and not args.replay:
        ap.error("pick --all, --scenario, --spec, or --replay")

    rows: list[dict] = []
    for spec in specs:
        rows.append(FleetSim(spec).run())
    if args.replay:
        with open(args.replay) as f:
            doc = json.load(f)
        rows.append(FleetSim.from_trace(doc).run())

    ok = True
    lines = [_row_line(r) for r in rows]
    for r, line in zip(rows, lines):
        print(line)
        if not r["envelope_ok"]:
            ok = False
            print(f"# envelope VIOLATED ({r['scenario']}): "
                  f"{'; '.join(r['violations'])}", file=sys.stderr)
    if args.jsonl:
        with open(args.jsonl, "a") as f:
            f.write("\n".join(lines) + "\n")
    return 0 if ok or not args.check else 1


if __name__ == "__main__":
    raise SystemExit(main())
