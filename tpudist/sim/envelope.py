"""The shared SLO-envelope checker CI gates scenario runs on.

Both execution paths — live replay and offline simulation — emit one
bench-JSONL row per scenario (``"metric": "scenario/{name}"`` with the
summary fields as extra keys).  This module is the one place that
decides whether such a row is inside its envelope, so the live bench,
the offline matrix, and the CI job cannot drift apart on what "green"
means.

Deliberately light: imports only :mod:`tpudist.sim.scenario` (pure
stdlib), so the CI gate can run it without jax/flax installed —
the same discipline as ``bench.py``'s heredoc asserts.

CLI::

    python -m tpudist.sim.envelope BENCH.jsonl --min-scenarios 5

exits nonzero when any scenario row violates its envelope, a builtin
scenario is missing, or fewer than ``--min-scenarios`` rows are found.
"""

from __future__ import annotations

import json

from tpudist.sim.scenario import BUILTIN, Envelope, ScenarioSpec

__all__ = ["scenario_rows", "check_row", "check_rows", "main"]


def scenario_rows(path: str) -> list[dict]:
    """The ``scenario/*`` rows of a bench-JSONL file (non-JSON lines —
    log noise around the bench output — are skipped)."""
    rows: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            metric = row.get("metric", "")
            if metric.startswith("scenario/"):
                rows.append(row)
    return rows


def check_row(row: dict, envelope: Envelope | None = None) -> list[str]:
    """Violations for one scenario row.  With no explicit envelope the
    scenario's BUILTIN envelope is used (re-checked from the row's raw
    fields — the emitter's own ``envelope_ok`` flag is evidence, not
    authority); a non-builtin scenario with no envelope passed is only
    held to its embedded verdict."""
    name = str(row.get("scenario")
               or row.get("metric", "")[len("scenario/"):])
    if envelope is None and name in BUILTIN:
        envelope = ScenarioSpec.from_dict(BUILTIN[name]).envelope
    bad = list(envelope.check(row)) if envelope is not None else []
    if row.get("envelope_ok") is False and not bad:
        bad.extend(row.get("violations")
                   or ["emitter flagged envelope_ok=false"])
    return bad


def check_rows(rows: list[dict], *, min_scenarios: int = 5,
               require_builtin: bool = True) -> tuple[bool, list[str]]:
    """(ok, report) for a matrix run: every row inside its envelope,
    at least ``min_scenarios`` distinct scenarios, and (by default)
    every BUILTIN scenario present."""
    report: list[str] = []
    ok = True
    seen: set[str] = set()
    for row in rows:
        name = str(row.get("scenario")
                   or row.get("metric", "")[len("scenario/"):])
        seen.add(name)
        bad = check_row(row)
        if bad:
            ok = False
            report.append(f"FAIL {name}: " + "; ".join(bad))
        else:
            report.append(
                f"ok   {name}: completed_ok={row.get('completed_ok')} "
                f"p99_wait={row.get('p99_queue_wait_s')}s "
                f"ups={row.get('scale_ups')} drains={row.get('drains')}")
    if len(seen) < min_scenarios:
        ok = False
        report.append(f"FAIL matrix: only {len(seen)} scenario(s), "
                      f"need >= {min_scenarios}")
    if require_builtin:
        missing = sorted(set(BUILTIN) - seen)
        if missing:
            ok = False
            report.append(f"FAIL matrix: builtin scenario(s) missing "
                          f"from the run: {missing}")
    return ok, report


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Gate a bench-JSONL file on per-scenario SLO "
                    "envelopes")
    ap.add_argument("jsonl", help="bench JSONL file (scenario/* rows)")
    ap.add_argument("--min-scenarios", type=int, default=5)
    ap.add_argument("--no-require-builtin", action="store_true",
                    help="don't demand every builtin scenario be present")
    args = ap.parse_args(argv)
    rows = scenario_rows(args.jsonl)
    ok, report = check_rows(rows, min_scenarios=args.min_scenarios,
                            require_builtin=not args.no_require_builtin)
    for line in report:
        print(line)
    print("ENVELOPES", "OK" if ok else "VIOLATED")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
