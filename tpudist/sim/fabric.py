"""In-memory coordination fabric for the offline fleet simulator.

The real fleet coordinates through :class:`tpudist.runtime.coord
.CoordClient` verbs over a TCP KV service; the simulator swaps in this
process-local stand-in (the ``FakeCoord`` discipline the unit tests
already use, promoted to library code) so the REAL ``Router`` and
``Autoscaler`` run unmodified: same key layout (``{ns}/inbox/...``,
``{ns}/done/...``, ``{ns}/draining/...``), same wire encodings, same
heartbeat-lease liveness — just a dict and a set instead of sockets.

Leases are explicit (:meth:`up` / :meth:`down`): simulated replicas
flip their own liveness at virtual-time boundaries instead of running
heartbeat threads, which is exactly what makes death/drain timing
deterministic under the virtual clock.

Chaos (ISSUE 12): :meth:`add_outage` declares coord-brownout windows on
the VIRTUAL clock — every client verb raises
:class:`~tpudist.runtime.faults.FaultInjected` (a ``ConnectionError``)
while one is open, which is exactly what the real store's
unreachability looks like to the router/replica/autoscaler brownout
paths.  Lease flips (:meth:`up`/:meth:`down`) model SERVER-side state
and stay outage-exempt: leases neither refresh nor lapse differently
because a client could not reach the store.
"""

from __future__ import annotations

import threading

from tpudist.runtime.faults import FaultInjected

__all__ = ["SimFabric"]


class SimFabric:
    """Process-local CoordClient stand-in: the KV + liveness verbs the
    router, autoscaler, and metrics planes reach for.

    ``clock`` (a zero-arg monotonic, normally ``VirtualClock
    .monotonic``) is only needed when outage windows are declared."""

    def __init__(self, clock=None) -> None:
        self.kv: dict[str, bytes] = {}
        self.live_set: set[str] = set()
        self.counters: dict[str, int] = {}
        self._lock = threading.Lock()
        self._clock = clock
        self._outages: list[tuple[float, float]] = []

    # -- chaos -------------------------------------------------------------

    def add_outage(self, start_s: float, end_s: float) -> None:
        """Declare a coord-brownout window ``[start_s, end_s)`` on the
        clock: every client verb raises ``FaultInjected`` inside it."""
        if self._clock is None:
            raise ValueError("SimFabric needs a clock= for outages")
        if not end_s > start_s >= 0:
            raise ValueError(
                f"bad outage window [{start_s}, {end_s})")
        self._outages.append((float(start_s), float(end_s)))

    def in_outage(self) -> bool:
        if self._clock is None or not self._outages:
            return False
        now = self._clock()
        return any(s <= now < e for s, e in self._outages)

    def _gate(self, op: str) -> None:
        if self.in_outage():
            raise FaultInjected(
                f"injected fault: sim coord outage ({op})")

    # -- KV verbs ----------------------------------------------------------

    def keys(self, prefix: str = "") -> list[str]:
        self._gate("keys")
        with self._lock:
            return [k for k in self.kv if k.startswith(prefix)]

    def get(self, key: str) -> bytes | None:
        self._gate("get")
        with self._lock:
            return self.kv.get(key)

    def set(self, key: str, value: bytes) -> None:
        self._gate("set")
        with self._lock:
            self.kv[key] = value

    def delete(self, key: str) -> None:
        self._gate("delete")
        with self._lock:
            self.kv.pop(key, None)

    def add(self, key: str, delta: int) -> int:
        self._gate("add")
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + int(delta)
            return self.counters[key]

    # -- liveness (heartbeat leases, simulated) ----------------------------

    def live(self) -> set[str]:
        self._gate("live")
        with self._lock:
            return set(self.live_set)

    def up(self, name: str) -> None:
        """Grant a lease (a simulated replica's first heartbeat)."""
        with self._lock:
            self.live_set.add(name)

    def down(self, name: str) -> None:
        """Lapse a lease (clean exit or simulated death)."""
        with self._lock:
            self.live_set.discard(name)

    # -- client lifecycle (API-compat no-ops) ------------------------------

    def clone(self) -> "SimFabric":
        return self

    def close(self) -> None:
        pass
