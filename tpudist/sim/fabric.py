"""In-memory coordination fabric for the offline fleet simulator.

The real fleet coordinates through :class:`tpudist.runtime.coord
.CoordClient` verbs over a TCP KV service; the simulator swaps in this
process-local stand-in (the ``FakeCoord`` discipline the unit tests
already use, promoted to library code) so the REAL ``Router`` and
``Autoscaler`` run unmodified: same key layout (``{ns}/inbox/...``,
``{ns}/done/...``, ``{ns}/draining/...``), same wire encodings, same
heartbeat-lease liveness — just a dict and a set instead of sockets.

Leases are explicit (:meth:`up` / :meth:`down`): simulated replicas
flip their own liveness at virtual-time boundaries instead of running
heartbeat threads, which is exactly what makes death/drain timing
deterministic under the virtual clock.
"""

from __future__ import annotations

import threading

__all__ = ["SimFabric"]


class SimFabric:
    """Process-local CoordClient stand-in: the KV + liveness verbs the
    router, autoscaler, and metrics planes reach for."""

    def __init__(self) -> None:
        self.kv: dict[str, bytes] = {}
        self.live_set: set[str] = set()
        self.counters: dict[str, int] = {}
        self._lock = threading.Lock()

    # -- KV verbs ----------------------------------------------------------

    def keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return [k for k in self.kv if k.startswith(prefix)]

    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self.kv.get(key)

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self.kv[key] = value

    def delete(self, key: str) -> None:
        with self._lock:
            self.kv.pop(key, None)

    def add(self, key: str, delta: int) -> int:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + int(delta)
            return self.counters[key]

    # -- liveness (heartbeat leases, simulated) ----------------------------

    def live(self) -> set[str]:
        with self._lock:
            return set(self.live_set)

    def up(self, name: str) -> None:
        """Grant a lease (a simulated replica's first heartbeat)."""
        with self._lock:
            self.live_set.add(name)

    def down(self, name: str) -> None:
        """Lapse a lease (clean exit or simulated death)."""
        with self._lock:
            self.live_set.discard(name)

    # -- client lifecycle (API-compat no-ops) ------------------------------

    def clone(self) -> "SimFabric":
        return self

    def close(self) -> None:
        pass
