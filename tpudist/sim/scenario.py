"""Declarative serve-fleet scenarios and their SLO envelopes.

A :class:`ScenarioSpec` is the replayable unit of "as many scenarios as
you can imagine": one document describing a workload SHAPE (arrival
process, prompt-length distribution, token budgets, deadline
distribution, tenant mix), the fleet it runs against (replica count,
service rates, autoscaler policy), and the :class:`Envelope` of SLO
outcomes the run must land inside.  The same spec drives both
execution paths:

* the LIVE replayer (:func:`tpudist.sim.workload.synthesize` ->
  ``Router.run(requests, arrivals=...)``) — real replicas, real chaos;
* the OFFLINE simulator (:class:`tpudist.sim.simulator.FleetSim`) —
  the real router/autoscaler policy code against simulated replicas,
  seconds instead of chaos-bench minutes.

Specs are plain dicts (JSON-shaped) parsed by
:meth:`ScenarioSpec.from_dict`, which REJECTS unknown keys — a typo'd
knob must fail parsing, not silently run the default scenario.
``BUILTIN`` holds the named scenario matrix CI runs on every push;
each entry's envelope is its regression gate.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any

__all__ = ["ScenarioSpec", "Envelope", "BUILTIN", "builtin", "names"]

ARRIVAL_KINDS = ("constant", "diurnal", "flash_crowd")
PROMPT_KINDS = ("uniform", "longtail")
DEADLINE_KINDS = ("none", "uniform", "adversarial")
# the FaultScript verbs, mirroring the TPUDIST_FAULT_* env knobs:
# KILL_AFTER_SEGMENTS, HEARTBEAT_STOP_AFTER_S, COORD_OUTAGE_AT_S/_S,
# ROUTER_KILL_AFTER_POLLS, FLIP_WIRE_BITS respectively
FAULT_KINDS = ("kill_replica", "drop_heartbeats", "coord_brownout",
               "kill_router", "corrupt_replica")


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"scenario spec: {msg}")


def _check_keys(what: str, d: dict, allowed: set[str],
                required: set[str] = frozenset()) -> None:
    _require(isinstance(d, dict), f"{what} must be a dict, got {d!r}")
    unknown = set(d) - allowed
    _require(not unknown, f"{what} has unknown keys {sorted(unknown)} "
                          f"(allowed: {sorted(allowed)})")
    missing = required - set(d)
    _require(not missing, f"{what} missing required keys "
                          f"{sorted(missing)}")


def _validate_arrival(a: dict) -> None:
    _check_keys("arrival", a,
                {"kind", "rate", "base_rate", "peak_rate", "period_s",
                 "spike_rate", "spike_at_s", "spike_width_s"}, {"kind"})
    kind = a["kind"]
    _require(kind in ARRIVAL_KINDS,
             f"arrival.kind {kind!r} not in {ARRIVAL_KINDS}")
    if kind == "constant":
        _require(float(a.get("rate", 0)) > 0, "constant needs rate > 0")
    elif kind == "diurnal":
        base = float(a.get("base_rate", 0))
        peak = float(a.get("peak_rate", 0))
        _require(0 < base <= peak,
                 f"diurnal needs 0 < base_rate <= peak_rate, got "
                 f"{base}/{peak}")
        _require(float(a.get("period_s", 0)) > 0,
                 "diurnal needs period_s > 0")
    elif kind == "flash_crowd":
        _require(float(a.get("base_rate", 0)) > 0,
                 "flash_crowd needs base_rate > 0")
        _require(float(a.get("spike_rate", 0))
                 > float(a.get("base_rate", 0)),
                 "flash_crowd needs spike_rate > base_rate")
        _require(float(a.get("spike_width_s", 0)) > 0,
                 "flash_crowd needs spike_width_s > 0")


def _validate_prompt(p: dict) -> None:
    _check_keys("prompt", p,
                {"kind", "lo", "hi", "typical", "tail", "tail_frac"},
                {"kind"})
    kind = p["kind"]
    _require(kind in PROMPT_KINDS,
             f"prompt.kind {kind!r} not in {PROMPT_KINDS}")
    if kind == "uniform":
        lo, hi = int(p.get("lo", 0)), int(p.get("hi", 0))
        _require(0 < lo <= hi, f"prompt needs 0 < lo <= hi, got {lo}/{hi}")
    else:
        lo = int(p.get("lo", 0))
        typ = int(p.get("typical", 0))
        tail = int(p.get("tail", 0))
        frac = float(p.get("tail_frac", 0.05))
        _require(0 < lo <= typ < tail,
                 f"longtail needs 0 < lo <= typical < tail, got "
                 f"{lo}/{typ}/{tail}")
        _require(0.0 < frac < 1.0,
                 f"longtail tail_frac must be in (0, 1), got {frac}")


def _validate_deadline(d: dict) -> None:
    _check_keys("deadline", d,
                {"kind", "lo", "hi", "tight_frac", "tight_s", "loose_s"},
                {"kind"})
    kind = d["kind"]
    _require(kind in DEADLINE_KINDS,
             f"deadline.kind {kind!r} not in {DEADLINE_KINDS}")
    if kind == "uniform":
        lo, hi = float(d.get("lo", 0)), float(d.get("hi", 0))
        _require(0 < lo <= hi,
                 f"deadline needs 0 < lo <= hi, got {lo}/{hi}")
    elif kind == "adversarial":
        _require(0.0 < float(d.get("tight_frac", 0)) < 1.0,
                 "adversarial needs tight_frac in (0, 1)")
        _require(0 < float(d.get("tight_s", 0))
                 < float(d.get("loose_s", 0)),
                 "adversarial needs 0 < tight_s < loose_s")


def _validate_tenant(t: dict) -> None:
    _check_keys("tenant", t,
                {"name", "weight", "prefix_tokens", "priority"},
                {"name", "weight"})
    _require(float(t["weight"]) > 0,
             f"tenant {t.get('name')!r} needs weight > 0")
    _require(int(t.get("prefix_tokens", 0)) >= 0,
             "tenant prefix_tokens must be >= 0")


def _validate_fault(f: dict) -> None:
    _check_keys("fault", f,
                {"kind", "at_s", "for_s", "rid", "at_poll", "every",
                 "count"}, {"kind"})
    kind = f["kind"]
    _require(kind in FAULT_KINDS,
             f"fault.kind {kind!r} not in {FAULT_KINDS}")
    if kind == "kill_replica":
        _check_keys("fault(kill_replica)", f, {"kind", "at_s", "rid"},
                    {"kind", "at_s", "rid"})
        _require(float(f["at_s"]) >= 0, "kill_replica needs at_s >= 0")
    elif kind == "drop_heartbeats":
        _check_keys("fault(drop_heartbeats)", f,
                    {"kind", "at_s", "for_s", "rid"},
                    {"kind", "at_s", "for_s", "rid"})
        _require(float(f["at_s"]) >= 0,
                 "drop_heartbeats needs at_s >= 0")
        _require(float(f["for_s"]) > 0,
                 "drop_heartbeats needs for_s > 0")
    elif kind == "coord_brownout":
        _check_keys("fault(coord_brownout)", f,
                    {"kind", "at_s", "for_s"}, {"kind", "at_s", "for_s"})
        _require(float(f["at_s"]) >= 0,
                 "coord_brownout needs at_s >= 0")
        _require(float(f["for_s"]) > 0,
                 "coord_brownout needs for_s > 0")
    elif kind == "kill_router":
        _check_keys("fault(kill_router)", f, {"kind", "at_poll"},
                    {"kind", "at_poll"})
        _require(int(f["at_poll"]) >= 1,
                 "kill_router needs at_poll >= 1")
    elif kind == "corrupt_replica":
        # byzantine replica: from at_s, every Nth committed payload has
        # a byte flipped AFTER framing (so the wire checksum is what
        # catches it); an optional count cap lets the replica "heal" —
        # the path golden-probe reinstatement is gated on
        _check_keys("fault(corrupt_replica)", f,
                    {"kind", "at_s", "rid", "every", "count"},
                    {"kind", "at_s", "rid"})
        _require(float(f["at_s"]) >= 0,
                 "corrupt_replica needs at_s >= 0")
        _require(int(f.get("every", 1)) >= 1,
                 "corrupt_replica needs every >= 1")
        _require(f.get("count") is None or int(f["count"]) >= 1,
                 "corrupt_replica count must be >= 1 when set")


_FLEET_DEFAULTS: dict[str, Any] = {
    "replicas": 1,
    "seconds_per_token": 0.002,
    "prefill_s": 0.005,
    "prefill_per_token_s": 0.0002,
    "warmup_s": 2.0,
    "publish_interval_s": 0.25,
    "wait_window_s": 15.0,
    "router_poll_s": 0.05,
    "autoscale": None,          # dict of AutoscaleConfig overrides
    # disaggregated fleet: when prefill_replicas > 0 the fleet splits
    # into a prefill pool and a decode pool (``replicas`` is ignored)
    # and each pool can run its own autoscaler policy — the two-signal
    # split the live Autoscaler(pool=...) instances implement
    "prefill_replicas": 0,
    "decode_replicas": 0,
    "autoscale_prefill": None,
    "autoscale_decode": None,
    # per-replica KV capacity the sim's decode occupancy model publishes
    # through serve/kv_blocks_{used,free} (0 disables the gauges)
    "kv_blocks_total": 0,
    # tiered-KV model (ISSUE 16): per-replica "HBM" prefix-chain
    # capacity in 16-token blocks (0 disables the chain model entirely)
    # and the host-tier capacity its LRU spills land in
    "prefix_cache_blocks": 0,
    "tier_blocks": 0,
    # priority preemption (ISSUE 19): "migrate" turns on the replicas'
    # pause/resume flow model — a higher-priority arrival preempts the
    # running best-effort request instead of queueing behind it, the
    # sim mirror of ``ServeLoop(preempt="migrate")``
    "preempt": "degrade",
    # observability plane (ISSUE 17): cadence of the real
    # scrape->TSDB->alert-rules path every sim runs on the virtual
    # clock (the default rule set from tpudist.obs.alerts; the
    # scenario's envelope.alerts pins which rules must/must not fire)
    "alert_scrape_s": 1.0,
}


@dataclass(frozen=True)
class Envelope:
    """The per-scenario SLO gate, asserted against the summary row a
    run emits (live bench or offline simulator — same schema).

    ``None`` bounds are unchecked.  ``decisions`` bounds the router's
    terminal decision counters: ``{"shed": {"min": 1, "max": 10}}``."""

    max_lost: int = 0
    max_p99_queue_wait_s: float | None = None
    max_recovery_s: float | None = None
    min_scale_ups: int = 0
    max_scale_ups: int | None = None
    min_drains: int = 0
    max_priority_bad: int | None = None
    max_burn_rate_300s: float | None = None
    max_replica_deaths: int | None = None
    min_router_recoveries: int = 0
    # data-plane integrity gates: quarantines the run must produce (a
    # corruption scenario that never quarantines is a failed detection),
    # reinstatements it must earn back, and the hard ceiling on
    # CORRUPTED terminals actually delivered to callers (0 is the whole
    # point of the checksummed wire)
    min_quarantines: int = 0
    min_reinstated: int = 0
    max_corrupted_terminals: int | None = None
    # prefix-cache affinity gate (ISSUE 14): the fleet-level hit rate a
    # shared-prefix workload must sustain — checked only when the row
    # reports one (a workload with no stamped hashes is exempt, not
    # failing at 0.0)
    min_prefix_hit_rate: float | None = None
    # tiered-KV gate (ISSUE 16): the fleet-wide BLOCK-level reuse rate
    # (local HBM + host-tier re-admit + peer pull, over all admitted
    # chain blocks) a cold-heavy shared-prefix workload must sustain —
    # checked only when the row reports one (the chain model off is
    # exempt, not failing at 0.0)
    min_global_hit_rate: float | None = None
    # disaggregated-serving gates (ISSUE 15): the TTFT ceiling the
    # prefill pool must hold under the mixed-length workload, and the
    # per-pool scale-up floors that prove the two control loops sized
    # their pools INDEPENDENTLY (one shared loop would show one signal)
    max_p99_ttft_s: float | None = None
    min_scale_ups_prefill: int = 0
    min_scale_ups_decode: int = 0
    # preemption gates (ISSUE 19): the queue-wait tail the PRIORITY
    # class alone must hold (the number preemption exists to protect —
    # the overall p99 is dominated by paused best-effort traffic and
    # would hide the win), and the preemption-volume floor that proves
    # the pause path actually ran rather than the fleet being oversized
    max_p99_priority_wait_s: float | None = None
    min_preemptions: int = 0
    decisions: dict = field(default_factory=dict)
    # alert-envelope (ISSUE 17): which alert RULES the run's real
    # scrape->TSDB->evaluate path must (and must not) have fired, read
    # from the row's ``alerts_fired`` list.  ``{"must_fire":
    # ["CoordOutage"], "must_not_fire": "*"}`` — the "*" wildcard means
    # any fired rule outside must_fire is a violation (the
    # zero-false-positive gate steady_state runs under).
    alerts: dict = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "Envelope":
        allowed = {f.name for f in dataclasses.fields(cls)}
        _check_keys("envelope", d, allowed)
        dec = d.get("decisions", {})
        for reason, bound in dec.items():
            _check_keys(f"envelope.decisions[{reason!r}]", bound,
                        {"min", "max"})
        al = d.get("alerts", {})
        _check_keys("envelope.alerts", al, {"must_fire", "must_not_fire"})
        mnf = al.get("must_not_fire", [])
        _require(mnf == "*" or isinstance(mnf, (list, tuple)),
                 "envelope.alerts.must_not_fire must be a rule list "
                 "or the wildcard \"*\"")
        return cls(**d)

    def check(self, row: dict) -> list[str]:
        """Violations of this envelope in a scenario summary ``row``
        (empty list = the run landed inside the envelope)."""
        bad: list[str] = []

        def num(key, default=0.0):
            v = row.get(key)
            return default if v is None else float(v)

        lost = num("lost_requests")
        if lost > self.max_lost:
            bad.append(f"lost_requests={lost:g} > max_lost={self.max_lost}")
        if self.max_p99_queue_wait_s is not None:
            p99 = num("p99_queue_wait_s")
            if p99 > self.max_p99_queue_wait_s:
                bad.append(f"p99_queue_wait_s={p99:.4g} > "
                           f"{self.max_p99_queue_wait_s}")
        if self.max_recovery_s is not None:
            rec = num("recovery_s")
            if rec > self.max_recovery_s:
                bad.append(f"recovery_s={rec:.4g} > {self.max_recovery_s}")
        ups = num("scale_ups")
        if ups < self.min_scale_ups:
            bad.append(f"scale_ups={ups:g} < min {self.min_scale_ups}")
        if self.max_scale_ups is not None and ups > self.max_scale_ups:
            bad.append(f"scale_ups={ups:g} > max {self.max_scale_ups}")
        if num("drains") < self.min_drains:
            bad.append(f"drains={num('drains'):g} < min {self.min_drains}")
        if self.max_priority_bad is not None:
            pb = num("priority_bad")
            if pb > self.max_priority_bad:
                bad.append(f"priority_bad={pb:g} > "
                           f"{self.max_priority_bad}")
        if self.max_burn_rate_300s is not None:
            br = num("burn_rate_300s")
            if br > self.max_burn_rate_300s:
                bad.append(f"burn_rate_300s={br:.4g} > "
                           f"{self.max_burn_rate_300s}")
        if self.max_replica_deaths is not None:
            deaths = num("replica_deaths")
            if deaths > self.max_replica_deaths:
                bad.append(f"replica_deaths={deaths:g} > "
                           f"{self.max_replica_deaths}")
        recov = num("router_recoveries")
        if recov < self.min_router_recoveries:
            bad.append(f"router_recoveries={recov:g} < min "
                       f"{self.min_router_recoveries}")
        if num("quarantines") < self.min_quarantines:
            bad.append(f"quarantines={num('quarantines'):g} < min "
                       f"{self.min_quarantines}")
        if num("reinstated") < self.min_reinstated:
            bad.append(f"reinstated={num('reinstated'):g} < min "
                       f"{self.min_reinstated}")
        if self.max_corrupted_terminals is not None:
            ct = num("corrupted_terminals")
            if ct > self.max_corrupted_terminals:
                bad.append(f"corrupted_terminals={ct:g} > "
                           f"{self.max_corrupted_terminals}")
        if (self.min_prefix_hit_rate is not None
                and row.get("prefix_hit_rate") is not None):
            phr = num("prefix_hit_rate")
            if phr < self.min_prefix_hit_rate:
                bad.append(f"prefix_hit_rate={phr:.4g} < min "
                           f"{self.min_prefix_hit_rate}")
        if (self.min_global_hit_rate is not None
                and row.get("global_hit_rate") is not None):
            ghr = num("global_hit_rate")
            if ghr < self.min_global_hit_rate:
                bad.append(f"global_hit_rate={ghr:.4g} < min "
                           f"{self.min_global_hit_rate}")
        if self.max_p99_ttft_s is not None:
            ttft = num("p99_ttft_s")
            if ttft > self.max_p99_ttft_s:
                bad.append(f"p99_ttft_s={ttft:.4g} > "
                           f"{self.max_p99_ttft_s}")
        if self.max_p99_priority_wait_s is not None:
            pw = num("p99_priority_wait_s")
            if pw > self.max_p99_priority_wait_s:
                bad.append(f"p99_priority_wait_s={pw:.4g} > "
                           f"{self.max_p99_priority_wait_s}")
        if num("preemptions") < self.min_preemptions:
            bad.append(f"preemptions={num('preemptions'):g} < min "
                       f"{self.min_preemptions}")
        for pool in ("prefill", "decode"):
            floor = getattr(self, f"min_scale_ups_{pool}")
            v = num(f"scale_ups_{pool}")
            if v < floor:
                bad.append(f"scale_ups_{pool}={v:g} < min {floor}")
        for reason, bound in self.decisions.items():
            v = num(f"decisions_{reason}")
            lo, hi = bound.get("min"), bound.get("max")
            if lo is not None and v < lo:
                bad.append(f"decisions_{reason}={v:g} < min {lo}")
            if hi is not None and v > hi:
                bad.append(f"decisions_{reason}={v:g} > max {hi}")
        if self.alerts:
            fired = row.get("alerts_fired")
            if fired is None:
                bad.append("alerts envelope set but the row carries no "
                           "alerts_fired (alert plane did not run)")
            else:
                fired = set(fired)
                must = list(self.alerts.get("must_fire", []))
                for rule in must:
                    if rule not in fired:
                        bad.append(f"alert {rule} did not fire "
                                   f"(fired: {sorted(fired) or 'none'})")
                must_not = self.alerts.get("must_not_fire", [])
                if must_not == "*":
                    extra = fired - set(must)
                    if extra:
                        bad.append("unexpected alerts fired: "
                                   f"{sorted(extra)}")
                else:
                    for rule in must_not:
                        if rule in fired:
                            bad.append(f"alert {rule} fired but is in "
                                       f"must_not_fire")
        return bad


@dataclass(frozen=True)
class ScenarioSpec:
    """One named, seeded, replayable scenario (see module docstring)."""

    name: str
    duration_s: float
    arrival: dict
    prompt: dict = field(
        default_factory=lambda: {"kind": "uniform", "lo": 4, "hi": 12})
    max_new: dict = field(
        default_factory=lambda: {"kind": "uniform", "lo": 8, "hi": 24})
    deadline: dict = field(default_factory=lambda: {"kind": "none"})
    tenants: tuple = ()
    faults: tuple = ()
    seed: int = 0
    fleet: dict = field(default_factory=dict)
    envelope: Envelope = field(default_factory=Envelope)

    def __post_init__(self):
        _require(bool(self.name), "name must be non-empty")
        _require(self.duration_s > 0,
                 f"duration_s must be > 0, got {self.duration_s}")
        _validate_arrival(self.arrival)
        _validate_prompt(self.prompt)
        _check_keys("max_new", self.max_new, {"kind", "lo", "hi", "value"},
                    {"kind"})
        _require(self.max_new["kind"] in ("uniform", "const"),
                 f"max_new.kind {self.max_new['kind']!r} not in "
                 f"('uniform', 'const')")
        if self.max_new["kind"] == "uniform":
            lo = int(self.max_new.get("lo", 0))
            hi = int(self.max_new.get("hi", 0))
            _require(0 < lo <= hi,
                     f"max_new needs 0 < lo <= hi, got {lo}/{hi}")
        else:
            _require(int(self.max_new.get("value", 0)) > 0,
                     "max_new const needs value > 0")
        _validate_deadline(self.deadline)
        for t in self.tenants:
            _validate_tenant(t)
        for f in self.faults:
            _validate_fault(f)
        _require(sum(1 for f in self.faults
                     if f["kind"] == "kill_router") <= 1,
                 "at most one kill_router fault per scenario")
        _check_keys("fleet", self.fleet, set(_FLEET_DEFAULTS))
        merged = {**_FLEET_DEFAULTS, **self.fleet}
        if int(merged["prefill_replicas"]) > 0:
            _require(int(merged["decode_replicas"]) >= 1,
                     "a disaggregated fleet needs decode_replicas >= 1")
        else:
            _require(int(merged["decode_replicas"]) == 0
                     and merged["autoscale_prefill"] is None
                     and merged["autoscale_decode"] is None,
                     "decode_replicas / per-pool autoscale need "
                     "prefill_replicas >= 1")
            _require(int(merged["replicas"]) >= 1,
                     "fleet.replicas must be >= 1")
        _require(float(merged["seconds_per_token"]) > 0,
                 "fleet.seconds_per_token must be > 0")
        _require(merged["preempt"] in ("degrade", "migrate"),
                 f"fleet.preempt must be 'degrade' or 'migrate', "
                 f"got {merged['preempt']!r}")
        # frozen dataclass: route the normalized fleet through __setattr__
        object.__setattr__(self, "fleet", merged)
        object.__setattr__(self, "tenants", tuple(self.tenants))
        object.__setattr__(self, "faults", tuple(self.faults))

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        allowed = {f.name for f in dataclasses.fields(cls)}
        _check_keys(f"scenario {d.get('name', '?')!r}", d, allowed,
                    {"name", "duration_s", "arrival"})
        kw = dict(d)
        env = kw.pop("envelope", None)
        if env is not None and not isinstance(env, Envelope):
            env = Envelope.from_dict(env)
        return cls(**kw, **({"envelope": env} if env is not None else {}))

    @classmethod
    def from_json(cls, path: str) -> "ScenarioSpec":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["tenants"] = list(self.tenants)
        d["faults"] = list(self.faults)
        return d


# -- the named scenario matrix (the CI regression suite) --------------------
#
# Each entry stresses a different policy surface; each envelope is the
# regression gate a future serving PR must keep green.  Rates are sized
# for the offline simulator's default service rate (2 ms/token, ~16
# token budgets => one replica saturates around 20-25 req/s).

_AUTOSCALE_FAST = {
    # scale-up after 3 sustained breach polls at 0.5 s cadence; drain
    # back after a long idle window — sim-seconds, not wall-seconds
    "min_replicas": 1, "max_replicas": 4, "target_wait_s": 0.5,
    "low_wait_s": 0.1, "quantile": 0.9, "breach_polls": 3,
    "idle_polls": 10, "up_cooldown_s": 8.0, "down_cooldown_s": 20.0,
    "poll_s": 0.5, "max_metric_age_s": 10.0,
}

BUILTIN: dict[str, dict] = {
    "steady_state": {
        "name": "steady_state",
        "duration_s": 30.0,
        "arrival": {"kind": "constant", "rate": 8.0},
        "seed": 11,
        "fleet": {"replicas": 1, "autoscale": dict(_AUTOSCALE_FAST)},
        "envelope": {
            "max_lost": 0,
            "max_p99_queue_wait_s": 0.5,
            "max_scale_ups": 0,      # steady load must not flap the fleet
            "decisions": {"completed": {"min": 150}},
            # the alert plane's zero-false-positive gate: a healthy
            # steady fleet must fire NOTHING (ISSUE 17)
            "alerts": {"must_fire": [], "must_not_fire": "*"},
        },
    },
    "diurnal_ramp": {
        "name": "diurnal_ramp",
        "duration_s": 90.0,
        "arrival": {"kind": "diurnal", "base_rate": 3.0,
                    "peak_rate": 40.0, "period_s": 60.0},
        "seed": 12,
        "fleet": {"replicas": 1, "autoscale": dict(_AUTOSCALE_FAST)},
        "envelope": {
            "max_lost": 0,
            "min_scale_ups": 1,      # the ramp must buy capacity
            "min_drains": 1,         # ... and the trough must return it
            "max_recovery_s": 90.0,
            "decisions": {"failed": {"max": 0}},
            # the peak saturates one replica before the scale-up lands,
            # so queue-wait MUST page — and nothing else may
            "alerts": {"must_fire": ["QueueWaitHigh"],
                       "must_not_fire": "*"},
        },
    },
    "flash_crowd": {
        "name": "flash_crowd",
        "duration_s": 60.0,
        "arrival": {"kind": "flash_crowd", "base_rate": 4.0,
                    "spike_rate": 120.0, "spike_at_s": 10.0,
                    "spike_width_s": 4.0},
        "seed": 13,
        "fleet": {"replicas": 1, "autoscale": dict(_AUTOSCALE_FAST)},
        "envelope": {
            "max_lost": 0,
            "min_scale_ups": 1,
            "max_recovery_s": 60.0,  # breach episode must end
            "decisions": {"failed": {"max": 0}},
            "alerts": {"must_fire": ["QueueWaitHigh"],
                       "must_not_fire": "*"},
        },
    },
    "shared_prefix_tenants": {
        "name": "shared_prefix_tenants",
        "duration_s": 30.0,
        "arrival": {"kind": "constant", "rate": 10.0},
        "tenants": [
            {"name": "sysA", "weight": 5.0, "prefix_tokens": 24,
             "priority": 0},
            {"name": "sysB", "weight": 3.0, "prefix_tokens": 48,
             "priority": 0},
            {"name": "paid", "weight": 2.0, "prefix_tokens": 12,
             "priority": 1},
        ],
        "seed": 14,
        "fleet": {"replicas": 2, "autoscale": dict(_AUTOSCALE_FAST)},
        "envelope": {
            "max_lost": 0,
            "max_p99_queue_wait_s": 0.5,
            "max_priority_bad": 0,   # paid traffic burns zero budget
            # the router's prefix-affinity steer must keep tenant
            # traffic landing where its prefix is cached: after each
            # tenant's first admission per replica, everything else
            # should hit (three tenants, two replicas — ≥ 0.5 is a
            # loose floor well below the steady-state rate)
            "min_prefix_hit_rate": 0.5,
            "decisions": {"completed": {"min": 200}},
            "alerts": {"must_fire": [], "must_not_fire": "*"},
        },
    },
    "cold_prefix_tenants": {
        "name": "cold_prefix_tenants",
        "duration_s": 30.0,
        "arrival": {"kind": "constant", "rate": 10.0},
        # eight tenants, each with a 4-block (64-token) system prefix:
        # the fleet-wide prefix working set is 32 blocks, but each
        # replica's "HBM" chain capacity holds only 12 — no single
        # replica can keep every tenant resident, which is exactly the
        # shape the host tier exists for.  LRU churn spills cold
        # tenants' chains into the tier; their next request re-admits
        # from host RAM instead of re-prefilling.  The tier budget is
        # deliberately TIGHT (4 blocks vs the steady ~5-block spill
        # residency): the tier still delivers the hit-rate floor, but
        # runs pinned at capacity — TierHeadroomLow must page (ISSUE 17)
        "tenants": [
            {"name": f"t{i}", "weight": 1.0, "prefix_tokens": 64,
             "priority": 0} for i in range(8)
        ],
        "seed": 22,
        "fleet": {"replicas": 2,
                  "prefix_cache_blocks": 12,
                  "tier_blocks": 4},
        "envelope": {
            "max_lost": 0,
            "max_p99_queue_wait_s": 1.0,
            # the tiered-KV gate: nearly every admitted chain block
            # after each tenant's cold first admission must be reused
            # (local HBM, tier re-admit, or peer pull) — without the
            # tier the 32-block working set thrashes 12-block HBM and
            # this floor is unreachable
            "min_global_hit_rate": 0.8,
            "decisions": {"completed": {"min": 200}},
            "alerts": {"must_fire": ["TierHeadroomLow"],
                       "must_not_fire": "*"},
        },
    },
    "long_tail_prompts": {
        "name": "long_tail_prompts",
        "duration_s": 40.0,
        "arrival": {"kind": "constant", "rate": 8.0},
        "prompt": {"kind": "longtail", "lo": 4, "typical": 16,
                   "tail": 512, "tail_frac": 0.06},
        "seed": 15,
        "fleet": {"replicas": 1, "autoscale": dict(_AUTOSCALE_FAST)},
        "envelope": {
            "max_lost": 0,
            "max_p99_queue_wait_s": 2.0,  # tail prompts queue behind
            "decisions": {"failed": {"max": 0}},
            "alerts": {"must_fire": [], "must_not_fire": "*"},
        },
    },
    "deadline_storm": {
        "name": "deadline_storm",
        "duration_s": 40.0,
        "arrival": {"kind": "flash_crowd", "base_rate": 6.0,
                    "spike_rate": 80.0, "spike_at_s": 8.0,
                    "spike_width_s": 4.0},
        "deadline": {"kind": "adversarial", "tight_frac": 0.3,
                     "tight_s": 0.08, "loose_s": 30.0},
        "seed": 16,
        "fleet": {"replicas": 1, "autoscale": dict(_AUTOSCALE_FAST)},
        "envelope": {
            "max_lost": 0,
            # tight deadlines under the spike MUST be shed/timed out at
            # admission (not served late, not failed): the SLO gate's
            # reason-to-decide regression
            "decisions": {"failed": {"max": 0},
                          "completed": {"min": 150}},
            # the spike sheds tight deadlines (SLO burn) while the queue
            # backs up behind it — BOTH pages, and nothing fleet-fatal
            "alerts": {"must_fire": ["QueueWaitHigh", "SLOBurnHigh"],
                       "must_not_fire": "*"},
        },
    },
    "replica_death_storm": {
        "name": "replica_death_storm",
        "duration_s": 45.0,
        "arrival": {"kind": "constant", "rate": 30.0},
        "seed": 17,
        # no scale-DOWNs here: the pre-kill fleet is lightly loaded and
        # a drain before the kills would leave zero replicas publishing
        # metrics (nothing for breach detection to see) — this scenario
        # gates death recovery, not idle drain
        "fleet": {"replicas": 3,
                  "autoscale": {**_AUTOSCALE_FAST, "idle_polls": 200}},
        # two of three replicas die mid-run: the survivor saturates,
        # the router redispatches every orphaned request, and the
        # autoscaler must buy the capacity back
        "faults": [
            {"kind": "kill_replica", "at_s": 5.0, "rid": "r1"},
            {"kind": "kill_replica", "at_s": 7.0, "rid": "r2"},
        ],
        "envelope": {
            "max_lost": 0,
            "max_replica_deaths": 2,
            "min_scale_ups": 1,
            "max_recovery_s": 45.0,
            "max_burn_rate_300s": 40.0,
            "decisions": {"failed": {"max": 0}},
            # two kills -> router/replica_deaths moves -> ReplicaLost
            # pages; the survivor saturates -> QueueWaitHigh.  A coord
            # outage here would be a false positive: the store is UP
            "alerts": {"must_fire": ["ReplicaLost", "QueueWaitHigh"],
                       "must_not_fire": "*"},
        },
    },
    "router_failover": {
        "name": "router_failover",
        "duration_s": 40.0,
        "arrival": {"kind": "flash_crowd", "base_rate": 5.0,
                    "spike_rate": 60.0, "spike_at_s": 8.0,
                    "spike_width_s": 4.0},
        "seed": 18,
        "fleet": {"replicas": 2, "autoscale": dict(_AUTOSCALE_FAST)},
        # the router dies mid-spike (~poll 200 at 0.05 s cadence); a
        # fresh router must rebuild its table from the journal, re-adopt
        # the live replicas, and finish every request
        "faults": [{"kind": "kill_router", "at_poll": 200}],
        "envelope": {
            "max_lost": 0,
            "min_router_recoveries": 1,
            "decisions": {"failed": {"max": 0},
                          "completed": {"min": 250}},
            # a router crash is NOT a replica death and NOT a coord
            # outage — only the spike's queue wait may page
            "alerts": {"must_fire": ["QueueWaitHigh"],
                       "must_not_fire": "*"},
        },
    },
    "silent_corruption": {
        "name": "silent_corruption",
        "duration_s": 30.0,
        "arrival": {"kind": "constant", "rate": 10.0},
        "seed": 20,
        # no scale-downs: the quarantine window leaves one active
        # replica, and draining IT would zero the fleet mid-probe
        "fleet": {"replicas": 2,
                  "autoscale": {**_AUTOSCALE_FAST, "idle_polls": 200}},
        # r1 goes byzantine at 3 s: every committed payload has a byte
        # flipped post-framing, for 8 payloads, then it "heals".  The
        # wire checksum must catch every flip BEFORE delivery (zero
        # corrupted terminals), the strike ledger must quarantine r1,
        # golden probes must burn through the residual corruption and
        # then reinstate it — all with zero lost requests, because
        # every rejected completion is redispatched
        "faults": [{"kind": "corrupt_replica", "at_s": 3.0,
                    "rid": "r1", "every": 1, "count": 8}],
        "envelope": {
            "max_lost": 0,
            "min_quarantines": 1,
            "min_reinstated": 1,
            "max_corrupted_terminals": 0,
            "max_replica_deaths": 0,
            "decisions": {"failed": {"max": 0}},
            "alerts": {"must_fire": ["QuarantineActive"],
                       "must_not_fire": "*"},
        },
    },
    "disagg_mixed_prompts": {
        "name": "disagg_mixed_prompts",
        "duration_s": 40.0,
        "arrival": {"kind": "constant", "rate": 20.0},
        # the disaggregation workload: mostly short prompts with a long
        # tail — on a unified fleet the tail's prefill stalls every
        # decoding lane behind it; split pools keep TTFT bounded
        "prompt": {"kind": "longtail", "lo": 4, "typical": 16,
                   "tail": 512, "tail_frac": 0.08},
        "max_new": {"kind": "uniform", "lo": 16, "hi": 48},
        "seed": 21,
        # both pools start at 1 and BOTH are undersized, but for
        # different resources: the prefill pool drowns in queue wait
        # (compute), the decode pool drowns in resident KV (memory).
        # Each pool's autoscaler watches only its own signal — the
        # decode loop's queue-wait target is parked out of reach so a
        # scale-up there can only come from the kv-pressure signal
        "fleet": {"prefill_replicas": 1, "decode_replicas": 1,
                  "prefill_per_token_s": 0.002,
                  "kv_blocks_total": 64,
                  "autoscale_prefill": {
                      **_AUTOSCALE_FAST, "max_replicas": 3,
                      "idle_polls": 200},
                  "autoscale_decode": {
                      **_AUTOSCALE_FAST, "max_replicas": 3,
                      "idle_polls": 200, "target_wait_s": 30.0,
                      "low_wait_s": 0.1, "min_kv_free_frac": 0.3}},
        "envelope": {
            "max_lost": 0,
            "max_p99_ttft_s": 6.0,
            "min_scale_ups_prefill": 1,
            "min_scale_ups_decode": 1,
            "decisions": {"failed": {"max": 0}},
            "alerts": {"must_fire": ["QueueWaitHigh"],
                       "must_not_fire": "*"},
        },
    },
    "priority_saturation": {
        "name": "priority_saturation",
        "duration_s": 30.0,
        "arrival": {"kind": "constant", "rate": 12.0},
        # one replica, preemption ON, no autoscaler: a flood of fat
        # best-effort budgets oversaturates the lane (~0.1 s per
        # request at the default service rate — ~1.2x capacity, so the
        # backlog grows all run and QueueWaitHigh pages), and the
        # steady paid stream can only hold its wait floor by PAUSING
        # whatever is running.  With preempt="degrade" the same
        # workload parks paid p99 behind the multi-second best-effort
        # backlog; the envelope's priority-wait ceiling is unreachable
        # there, which is the regression gate on the preemption path.
        "max_new": {"kind": "const", "value": 44},
        "tenants": [
            {"name": "batch", "weight": 8.0, "priority": 0},
            {"name": "paid", "weight": 2.0, "priority": 1},
        ],
        "seed": 23,
        "fleet": {"replicas": 1, "preempt": "migrate"},
        "envelope": {
            "max_lost": 0,
            "max_priority_bad": 0,    # paid burns zero SLO budget
            "max_p99_priority_wait_s": 0.5,
            "min_preemptions": 5,     # the pause path must actually run
            "decisions": {"failed": {"max": 0}},
            "alerts": {"must_fire": ["QueueWaitHigh"],
                       "must_not_fire": "*"},
        },
    },
    "coord_brownout": {
        "name": "coord_brownout",
        "duration_s": 35.0,
        "arrival": {"kind": "constant", "rate": 8.0},
        "seed": 19,
        "fleet": {"replicas": 2, "autoscale": dict(_AUTOSCALE_FAST)},
        # the coord store goes dark for 6 s: in-flight decode keeps
        # running, completions buffer and flush on reconnect, and
        # NOBODY gets declared dead (stale, not lost)
        "faults": [{"kind": "coord_brownout", "at_s": 8.0, "for_s": 6.0}],
        "envelope": {
            "max_lost": 0,
            "max_replica_deaths": 0,
            "max_burn_rate_300s": 25.0,
            "decisions": {"failed": {"max": 0}},
            # the headline case from ISSUE 17: the scraper's collect()
            # round-trips fail during the brownout -> fleet/coord_up
            # drops -> CoordOutage pages.  ReplicaLost must NOT fire:
            # stale is not dead
            "alerts": {"must_fire": ["CoordOutage"],
                       "must_not_fire": "*"},
        },
    },
}


def names() -> list[str]:
    return sorted(BUILTIN)


def builtin(name: str) -> ScenarioSpec:
    """The named builtin scenario, parsed and validated."""
    if name not in BUILTIN:
        raise KeyError(
            f"unknown scenario {name!r} (have: {', '.join(names())})")
    return ScenarioSpec.from_dict(BUILTIN[name])
