"""The offline fleet simulator: real control-plane code, virtual time.

The point is NOT a queueing model of the fleet — it is the fleet's
actual decision code on a synthetic data plane.  :class:`FleetSim` runs
the real :class:`tpudist.runtime.router.Router` event loop and the real
:class:`tpudist.runtime.autoscaler.Autoscaler` policy, unmodified,
against:

* a :class:`VirtualClock` injected as the router's ``clock``/``wall``/
  ``sleeper`` and the autoscaler's ``clock`` — every sleep ADVANCES
  simulated time instead of burning wall time, so a 90-second diurnal
  scenario replays in well under a second;
* a :class:`~tpudist.sim.fabric.SimFabric` in place of the coord TCP
  service — same key layout, same wire encodings;
* :class:`SimReplica` data planes in place of real ``ServeLoop``
  processes: each consumes its inbox through the REAL request decoder,
  serves FIFO at a configured seconds-per-token rate (recorded
  ``serve/seconds_per_token`` EMAs when replaying a trace), commits
  completions through the real done-key protocol, and publishes
  ``MetricsPublisher``-shaped snapshots — windowed queue-wait
  histograms included — so the router's SLO admission and the
  autoscaler's target tracking read exactly the signals they read in
  production.

Because the policy code is shared, a simulated run emits the same
decision counters, the same autoscaler ``decision_log``, and a summary
row in the same bench-JSONL schema as a live run — which is what makes
scenario envelopes (:class:`tpudist.sim.scenario.Envelope`) meaningful
as CI gates, and what the sim-vs-live agreement check in ``bench.py``
leans on.
"""

from __future__ import annotations

import time

import numpy as np

from tpudist import obs
from tpudist.models.kv_pages import chain_hashes
from tpudist.obs.alerts import AlertManager, default_rules
from tpudist.obs.registry import values_to_hist
from tpudist.obs.tsdb import TSDB, FleetScraper
from tpudist.runtime import faults, wire
from tpudist.runtime.autoscaler import AutoscaleConfig, Autoscaler
from tpudist.runtime.router import (
    GoldenProbe,
    QuarantineConfig,
    Router,
    _decode_request,
)
from tpudist.sim.fabric import SimFabric
from tpudist.sim.scenario import Envelope, ScenarioSpec
from tpudist.sim.workload import (
    Workload,
    service_rates_from_trace,
    synthesize,
    workload_from_trace,
)

__all__ = ["VirtualClock", "SimReplica", "FleetSim"]

# simulated epoch: virtual wall time starts here (any fixed base works —
# deadlines are relative arithmetic — but a realistic epoch keeps
# recorded docs plausible to tooling that renders wall stamps)
_WALL_BASE = 1_750_000_000.0


class VirtualClock:
    """Simulated time: a monotonic origin at 0 and a wall clock at a
    fixed epoch, both advanced EXPLICITLY by the simulation loop.
    Injected wherever production code takes ``clock=``/``wall=``."""

    def __init__(self, wall_base: float = _WALL_BASE) -> None:
        self._now = 0.0
        self._wall_base = float(wall_base)

    def monotonic(self) -> float:
        return self._now

    def wall(self) -> float:
        return self._wall_base + self._now

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError(f"time only moves forward, got dt={dt}")
        self._now += dt


class SimReplica:
    """One simulated serve replica: the data-plane contract of a
    ``ReplicaWorker`` (inbox -> FIFO service -> done key; registration,
    lease, metrics snapshots; graceful-drain close path) at a scalar
    service rate instead of a model.

    Doubles as its own "process" for the autoscaler's spawner contract:
    ``poll()`` is ``None`` while running, ``0`` after departure, and
    ``replica_index`` matches the ``r{rank}`` id the pending-joiner
    check looks for."""

    def __init__(self, fabric: SimFabric, clock: VirtualClock, *,
                 rank: int, namespace: str,
                 role: str = "both",
                 seconds_per_token: float = 0.002,
                 prefill_s: float = 0.005,
                 prefill_per_token_s: float = 0.0002,
                 warmup_s: float = 0.0,
                 publish_interval_s: float = 0.25,
                 wait_window_s: float = 15.0,
                 kv_blocks_total: int = 0,
                 prefix_cache_blocks: int = 0,
                 tier_blocks: int = 0,
                 preempt: str = "degrade") -> None:
        self.fabric = fabric
        self.clock = clock
        self.rank = int(rank)
        self.replica_index = int(rank)   # the spawner/joiner contract
        self.rid = f"r{rank}"
        self.ns = namespace
        # the disaggregated stage split (ISSUE 15): a "prefill" replica
        # serves only the prompt pass and commits reason="handoff"; a
        # "decode" replica serves a handed-off request at decode cost
        # only (the pages arrived with it); "both" is the unified shape
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both/prefill/decode, "
                             f"got {role!r}")
        self.role = role
        self.spt = float(seconds_per_token)
        self.prefill_s = float(prefill_s)
        self.prefill_per_token_s = float(prefill_per_token_s)
        self.publish_interval_s = float(publish_interval_s)
        self.wait_window_s = float(wait_window_s)
        # synthetic KV occupancy (decode-pool autoscale signal): each
        # resident request pins ceil((prompt + budget) / 16) of these
        self.kv_blocks_total = int(kv_blocks_total)
        # priority preemption (ISSUE 19): "migrate" makes this replica
        # the flow-model mirror of ``ServeLoop(preempt="migrate")`` — a
        # strictly-higher-priority arrival PAUSES the running request
        # (its remaining service time parks in ``_paused`` and it
        # re-queues at the FRONT, the sim analogue of export -> host
        # tier -> re-adopt) and admission picks priority-first.  The
        # default keeps every pre-migration scenario byte-stable.
        if preempt not in ("degrade", "migrate"):
            raise ValueError(f"preempt must be degrade/migrate, "
                             f"got {preempt!r}")
        self.preempt = preempt
        self.preempted = 0
        self.resumed = 0
        self._paused: dict[str, float] = {}   # rid -> remaining service s
        self.all_waits_priority: list[float] = []
        self._obs_preempted = obs.counter("serve/preempted", unit="reqs")
        self._obs_resumed = obs.counter("serve/resumed", unit="reqs")
        self.alive = True
        self.killed = False
        self.served = 0
        self.all_waits: list[float] = []          # every queue wait (sim s)
        self.all_ttfts: list[float] = []          # first-token latencies
        self._live = False
        self._live_at = clock.monotonic() + max(0.0, float(warmup_s))
        self._stopping = False
        self._queue: list[tuple] = []             # (req, enqueued_at)
        self._cur: tuple | None = None            # (req, finish_at)
        self._waits: list[tuple[float, float]] = []   # (t, wait) window
        self._next_pub = self._live_at
        # coord brownout: commits that can't reach the fabric park here
        # and flush on the next step after the window — the SimReplica
        # mirror of ReplicaWorker's bounded done buffer
        self._done_buf: list[tuple[str, bytes]] = []
        self._hb_resume_at: float | None = None
        # corrupt_replica chaos: every Nth framed commit gets a byte
        # flipped (None = healthy); _corrupt_left caps the episode so
        # the replica can heal and earn reinstatement
        self._corrupt_every: int | None = None
        self._corrupt_left: int | None = None
        self._commits = 0
        # prefix-affinity accounting: recently admitted prefix hashes
        # (the SimReplica mirror of ServeLoop._affinity_recent) — a
        # request whose stamped hash is already here would have hit the
        # replica's prefix cache.  Published at {ns}/prefix/{rid} so the
        # ROUTER's affinity steer runs the same code path offline.
        self._affinity: dict[int, None] = {}
        self._prefix_pub: tuple | None = None
        self._prefix_refresh_at = 0.0
        self.prefix_requests = 0
        self.prefix_hits = 0
        # tiered-KV cost model (ISSUE 16): a bounded "HBM" set of
        # resident prefix-chain block hashes (PR 14 rolling chain at the
        # sim's fixed block size 16) plus a bounded host-tier set that
        # catches LRU spills — the SimReplica mirror of BlockPool's
        # prefix cache over HostTier.  Coverage of an admitted prompt's
        # leading blocks skips that span's prefill cost, which is what
        # makes local/tier/pull hit rates and the TTFT win measurable
        # offline.  0 capacity disables the model (and the chains half
        # of the prefix publish), keeping pre-tier scenarios byte-stable.
        self.prefix_cache_blocks = int(prefix_cache_blocks)
        self.tier_blocks = int(tier_blocks)
        self._hbm_chains: dict[int, None] = {}   # LRU, insertion order
        self._tier_chains: dict[int, None] = {}  # LRU, insertion order
        self.chain_blocks_total = 0
        self.chain_blocks_local = 0
        self.chain_blocks_tier = 0
        self.chain_blocks_pull = 0
        self.tier_spills = 0
        self.pull_exports = 0
        # registration precedes the first heartbeat, exactly like a real
        # joiner mid-warmup (the router's join grace covers this window)
        import json
        fabric.set(f"{namespace}/replica/{self.rid}",
                   json.dumps({"replica_id": self.rid,
                               "rank": self.rank,
                               "role": self.role}).encode())

    # -- the spawner/process contract --------------------------------------

    def poll(self):
        return None if self.alive else 0

    # -- chaos (the FaultScript verbs) -------------------------------------

    def kill(self) -> None:
        """SIGKILL equivalent: the lease lapses (server-side TTL), the
        consumed-but-unserved queue vanishes, the registration stays as
        residue — the router's death path sweeps and redispatches."""
        self.alive = False
        self.killed = True
        self._queue.clear()
        self._cur = None
        self._done_buf.clear()
        self.fabric.down(f"{self.ns}:{self.rid}")

    def drop_heartbeats(self, for_s: float) -> None:
        """HEARTBEAT_STOP equivalent: the lease lapses but the replica
        keeps serving — the false-positive-death shape whose duplicate
        done writes the router's consumption dedupes.  The lease
        returns after ``for_s`` virtual seconds."""
        self.fabric.down(f"{self.ns}:{self.rid}")
        self._hb_resume_at = self.clock.monotonic() + float(for_s)

    def corrupt(self, *, every: int = 1, count: int | None = None) -> None:
        """FLIP_WIRE_BITS equivalent: from now on, every ``every``-th
        committed payload has one byte flipped AFTER framing — silent
        corruption the router's wire checksum must catch.  ``count``
        caps the episode (the replica heals), which is what lets the
        quarantine's golden probes eventually pass."""
        self._corrupt_every = max(1, int(every))
        self._corrupt_left = None if count is None else int(count)

    # -- service model -----------------------------------------------------

    def _prefill_s_of(self, req, covered_tokens: int = 0) -> float:
        prompt = int(np.asarray(req.prompt).size)
        billable = max(0, prompt - int(covered_tokens))
        return self.prefill_s + billable * self.prefill_per_token_s

    def _service_s(self, req, covered_tokens: int = 0) -> float:
        if self.role == "prefill":
            return self._prefill_s_of(req, covered_tokens)
        if getattr(req, "kv_handoff", None) is not None:
            # adopted pages: the prompt pass already ran upstream
            return int(req.max_new_tokens) * self.spt
        return (self._prefill_s_of(req, covered_tokens)
                + int(req.max_new_tokens) * self.spt)

    def _maybe_preempt(self, now: float) -> bool:
        """Pause the running request when a strictly-higher-priority
        one waits (preempt="migrate" only): remaining service parks in
        ``_paused`` and the request re-queues at the FRONT — the flow
        model of ServeLoop's export -> park -> resume, byte-exactness
        included (the sim data plane is deterministic either way).
        Returns True when it preempted (the caller re-picks)."""
        if (self.preempt != "migrate" or self.role == "prefill"
                or self._cur is None or not self._queue):
            return False
        req, enq_t, start, finish_at, covered = self._cur
        curp = int(getattr(req, "priority", 0) or 0)
        top = max(int(getattr(r, "priority", 0) or 0)
                  for r, _ in self._queue)
        if top <= curp:
            return False
        self._paused[str(req.rid)] = finish_at - now
        self._queue.insert(0, (req, enq_t))
        self._cur = None
        self.preempted += 1
        self._obs_preempted.inc()
        if req.trace is not None:
            obs.events.record("preempt", trace=req.trace.trace_id,
                              replica=self.rid,
                              remaining_s=round(finish_at - now, 6))
        return True

    # -- tiered prefix-chain model (ISSUE 16) -------------------------------

    def _hbm_insert(self, h: int) -> None:
        """MRU-insert one chain hash into the bounded "HBM" set; LRU
        overflow spills into the tier set (the sim's host-RAM spill),
        whose own overflow drops the oldest entry outright."""
        self._hbm_chains.pop(h, None)
        self._hbm_chains[h] = None
        while len(self._hbm_chains) > self.prefix_cache_blocks:
            old = next(iter(self._hbm_chains))
            self._hbm_chains.pop(old)
            if self.tier_blocks > 0:
                self.tier_spills += 1
                self._tier_chains.pop(old, None)
                self._tier_chains[old] = None
                while len(self._tier_chains) > self.tier_blocks:
                    self._tier_chains.pop(next(iter(self._tier_chains)))

    def _pulled_chain(self, req) -> set[int]:
        """The chain hashes a router-initiated peer pull delivered with
        this request (``prefix_ref`` points at the owner's synthetic
        export payload) — the sim analogue of ``install_prefix``."""
        ref = getattr(req, "prefix_ref", None)
        if ref is None or self.prefix_cache_blocks <= 0:
            return set()
        import json
        try:
            raw = self.fabric.get(str(ref))
        except ConnectionError:
            return set()
        if raw is None:
            return set()
        try:
            doc = json.loads(raw.decode())
            return {int(h) for h in doc.get("chain", [])}
        except (ValueError, UnicodeDecodeError, AttributeError):
            return set()

    def _admit_chains(self, req) -> int:
        """Account an admission against the chain caches and return the
        covered leading tokens (whole blocks whose KV the replica did
        not have to recompute: HBM hit, tier re-admit, or peer pull).
        Admitting then caches the prompt's full chain locally, exactly
        like a real prefill populating the prefix cache."""
        if (self.prefix_cache_blocks <= 0
                or getattr(req, "kv_handoff", None) is not None):
            return 0
        chain = chain_hashes(
            [int(t) for t in np.asarray(req.prompt).tolist()], 16)
        if not chain:
            return 0
        pulled = self._pulled_chain(req)
        covered = 0
        for h in chain:
            if h in self._hbm_chains:
                self.chain_blocks_local += 1
            elif h in self._tier_chains:
                # tier re-admit: the page comes back from host RAM
                self._tier_chains.pop(h)
                self.chain_blocks_tier += 1
            elif h in pulled:
                self.chain_blocks_pull += 1
            else:
                break
            covered += 1
        self.chain_blocks_total += len(chain)
        for h in chain:
            self._hbm_insert(h)
        return covered * 16

    def _kv_blocks_of(self, req) -> int:
        prompt = int(np.asarray(req.prompt).size)
        return -(-(prompt + int(req.max_new_tokens)) // 16)

    def _kv_used(self) -> int:
        resident = [r for r, _ in self._queue]
        if self._cur is not None:
            resident.append(self._cur[0])
        return sum(self._kv_blocks_of(r) for r in resident)

    def _flush_done_buffer(self) -> None:
        while self._done_buf:
            key, payload = self._done_buf[0]
            try:
                self.fabric.set(key, payload)
            except ConnectionError:
                return
            self._done_buf.pop(0)

    def _commit(self, req, reason: str, tokens: list[int],
                extra: dict | None = None) -> None:
        # framed like a real worker's commit, so the router's checksum
        # verification (and the corrupt_replica chaos below) exercises
        # the same decode path as production
        payload = wire.encode_record("completion", {
            "key": str(req.rid), "tokens": tokens,
            "reason": reason, "replica": self.rid, **(extra or {})})
        self._commits += 1
        if (self._corrupt_every is not None
                and self._commits % self._corrupt_every == 0
                and (self._corrupt_left is None or self._corrupt_left > 0)):
            if self._corrupt_left is not None:
                self._corrupt_left -= 1
            pos = min(len(payload) - 1, max(9, len(payload) // 2))
            payload = (payload[:pos] + bytes([payload[pos] ^ 0x10])
                       + payload[pos + 1:])
        key = f"{self.ns}/done/{req.rid}"
        try:
            self._flush_done_buffer()
            if self._done_buf:   # still in the outage: keep order
                raise ConnectionError("sim coord outage")
            self.fabric.set(key, payload)
        except ConnectionError:
            self._done_buf.append((key, payload))
        self.served += 1
        if req.trace is not None:
            obs.events.record("done_commit", trace=req.trace.trace_id,
                              replica=self.rid, reason=reason,
                              tokens=len(tokens))

    def _publish(self) -> None:
        import json
        now = self.clock.monotonic()
        horizon = now - self.wait_window_s
        self._waits = [(t, w) for t, w in self._waits if t >= horizon]
        snap = {
            "rank": self.rank,
            # REAL wall stamp: collect()'s staleness cutoff measures
            # real seconds since publish, and the whole sim runs in
            # well under one — virtual stamps would look hours stale
            "published_at": time.time(),
            "gauges": {
                "serve/queue_depth": {"value": float(len(self._queue))},
                "serve/seconds_per_token": {"value": self.spt},
            },
            # the preempt/resume counters ride the snapshot exactly as
            # a live ServeLoop publishes them (empty dict when the mode
            # is off, keeping pre-migration snapshots byte-stable)
            "counters": (
                {"serve/preempted": {"value": float(self.preempted)},
                 "serve/resumed": {"value": float(self.resumed)}}
                if self.preempt == "migrate" else {}),
            "histograms": {},
        }
        if self.kv_blocks_total > 0:
            used = min(self._kv_used(), self.kv_blocks_total)
            snap["gauges"]["serve/kv_blocks_used"] = {"value": float(used)}
            snap["gauges"]["serve/kv_blocks_free"] = {
                "value": float(self.kv_blocks_total - used)}
        if self.tier_blocks > 0:
            # mirror the live TieredKV gauges (tpudist/models/kv_tier.py)
            # so the fleet scraper's tier-headroom derivation — and the
            # TierHeadroomLow alert rule — runs on the sim's spill tier
            # exactly as on a real one.  A sim "block" is 16 tokens; the
            # byte scale is arbitrary but consistent across both gauges.
            block_bytes = 16 * 1024
            snap["gauges"]["serve/tier_bytes"] = {
                "value": float(len(self._tier_chains) * block_bytes)}
            snap["gauges"]["serve/tier_budget_bytes"] = {
                "value": float(self.tier_blocks * block_bytes)}
        if self._waits:
            snap["histograms"]["serve/queue_wait_s"] = values_to_hist(
                [w for _, w in self._waits], unit="s")
        try:
            self.fabric.set(f"{self.ns}/metrics/{self.rank}",
                            json.dumps(snap).encode())
        except ConnectionError:
            pass   # latest-wins snapshots: the next publish catches up
        if self.prefix_cache_blocks > 0:
            # tiered summary: resident chains (HBM + tier), the sim's
            # fixed block size, weights version, and a wall stamp in the
            # VIRTUAL wall domain — the router's PrefixDirectory runs on
            # the injected VirtualClock.wall, so its TTL measures these
            # stamps in sim-seconds.  Age-based republish keeps a
            # steady-state summary from going stale mid-scenario.
            summ = (tuple(self._affinity), tuple(self._hbm_chains),
                    tuple(self._tier_chains))
            if (summ != self._prefix_pub
                    or now >= self._prefix_refresh_at):
                try:
                    self.fabric.set(
                        f"{self.ns}/prefix/{self.rid}",
                        wire.encode_record("prefix", {
                            "replica": self.rid,
                            "hashes": list(summ[0])[-64:],
                            "chains": list(self._hbm_chains),
                            "tiered": list(self._tier_chains),
                            "block_size": 16,
                            "version": 0,
                            "at": self.clock.wall()}))
                    self._prefix_pub = summ
                    self._prefix_refresh_at = now + 5.0
                except ConnectionError:
                    pass
        else:
            summ = tuple(self._affinity)
            if summ != self._prefix_pub:
                try:
                    self.fabric.set(
                        f"{self.ns}/prefix/{self.rid}",
                        wire.encode_record("prefix", {
                            "replica": self.rid,
                            "hashes": list(summ)[-64:]}))
                    self._prefix_pub = summ
                except ConnectionError:
                    pass
        self._next_pub = now + self.publish_interval_s

    def _serve_pulls(self) -> None:
        """Answer the router's pull-mode KV export requests
        (``{ns}/pullreq/{rid}/``): compute the leading chain run this
        replica actually holds (HBM or tier), publish a synthetic
        payload carrying those hashes, and commit the pulldone record —
        the SimReplica mirror of ``ReplicaWorker._serve_pulls`` over
        ``ServeLoop.export_prefix``.  A run it does not hold commits
        ``ref=None`` (the requester re-prefills, byte-identically)."""
        import json
        for key in sorted(self.fabric.keys(
                f"{self.ns}/pullreq/{self.rid}/")):
            raw = self.fabric.get(key)
            self.fabric.delete(key)
            if raw is None:
                continue
            try:
                doc = wire.decode_record(raw, expect="pullreq",
                                         namespace=self.ns, key=key,
                                         replica=self.rid)
            except wire.WireError:
                continue
            k = str(doc.get("key"))
            chain = chain_hashes(
                [int(t) for t in doc.get("prompt", [])], 16)
            run: list[int] = []
            for h in chain:
                if h in self._hbm_chains or h in self._tier_chains:
                    run.append(h)
                else:
                    break
            ref = None
            if run:
                ref = f"{self.ns}/kv/pull-{k}"
                self.fabric.set(ref, json.dumps(
                    {"chain": run, "block_size": 16,
                     "version": 0}).encode())
                self.pull_exports += 1
            self.fabric.set(
                f"{self.ns}/pulldone/{k}",
                wire.encode_record("pulldone", {
                    "key": k, "ref": ref, "owner": self.rid}))

    def step(self) -> None:
        """Advance the replica to the clock's current instant: go live
        after warmup, consume the inbox, finish/start service, publish
        metrics, and run the graceful close path once stopped.  A coord
        brownout makes the fabric verbs raise; the replica rides it out
        exactly like a real worker — keep serving what it has, buffer
        the commits, skip the polls."""
        if not self.alive:
            return
        now = self.clock.monotonic()
        if now < self._live_at:
            return
        if (self._hb_resume_at is not None
                and now >= self._hb_resume_at):
            # the dropped heartbeat returns: the lease re-establishes
            self.fabric.up(f"{self.ns}:{self.rid}")
            self._hb_resume_at = None
        if not self._live:
            self._live = True
            self.fabric.up(f"{self.ns}:{self.rid}")
            try:
                self._publish()
            except ConnectionError:
                pass

        self._flush_done_buffer()
        try:
            if (self.fabric.get(f"{self.ns}/stop") is not None
                    or self.fabric.get(f"{self.ns}/stop/{self.rid}")
                    is not None):
                self._stopping = True

            # consume the inbox through the real decoder (also the final
            # sweep while stopping: zero-loss drain means nothing
            # accepted is ever abandoned)
            inbox = f"{self.ns}/inbox/{self.rid}/"
            for key in sorted(self.fabric.keys(inbox)):
                raw = self.fabric.get(key)
                self.fabric.delete(key)
                if raw is None:
                    continue
                self._queue.append((_decode_request(raw), now))

            self._serve_pulls()
        except ConnectionError:
            inbox = f"{self.ns}/inbox/{self.rid}/"

        # serve: finish whatever is due, start whatever fits — several
        # per step when service times are shorter than the quantum
        while True:
            if self._cur is not None:
                req, enq_t, start, finish_at, covered = self._cur
                if now < finish_at:
                    if not self._maybe_preempt(now):
                        break
                    continue
                if self.role == "prefill":
                    # stage done: first token exists, KV migrated.  The
                    # ref is synthetic (the sim carries no pages) — the
                    # decode side's adopted-cost model keys off the stub
                    self.all_ttfts.append(finish_at - enq_t)
                    self._commit(req, "handoff", [],
                                 extra={"handoff_ref":
                                        f"sim://{req.rid}"})
                else:
                    if getattr(req, "kv_handoff", None) is None:
                        # unified service: the first token landed when
                        # this replica's own prompt pass finished
                        self.all_ttfts.append(
                            start + self._prefill_s_of(req, covered)
                            - enq_t)
                    self._commit(req, "length",
                                 list(range(int(req.max_new_tokens))))
                self._cur = None
            if not self._queue:
                break
            if self.preempt == "migrate":
                # priority-first admission, FIFO within a class — the
                # sim mirror of ServeLoop's migrate-mode admit_free
                sel = max(range(len(self._queue)),
                          key=lambda i: (int(getattr(
                              self._queue[i][0], "priority", 0) or 0),
                              -i))
            else:
                sel = 0
            req, enq_t = self._queue.pop(sel)
            remaining = self._paused.pop(str(req.rid), None)
            wait = now - enq_t
            if remaining is None:
                self._waits.append((now, wait))
                self.all_waits.append(wait)
                if int(getattr(req, "priority", 0) or 0) > 0:
                    self.all_waits_priority.append(wait)
            else:
                # a paused lane resuming: its wait was already counted
                # at first admission
                self.resumed += 1
                self._obs_resumed.inc()
                if req.trace is not None:
                    obs.events.record("resume", trace=req.trace.trace_id,
                                      replica=self.rid)
            if (req.deadline_s is not None
                    and self.clock.wall() > req.deadline_s):
                # expired while queued: the replica-side deadline kill
                self._commit(req, "timeout", [])
                continue
            phash = getattr(req, "prefix_hash", None)
            hit = False
            if phash is not None:
                self.prefix_requests += 1
                hit = int(phash) in self._affinity
                self.prefix_hits += int(hit)
                self._affinity.pop(int(phash), None)
                self._affinity[int(phash)] = None
                while len(self._affinity) > 128:
                    self._affinity.pop(next(iter(self._affinity)))
            covered = 0 if remaining is not None \
                else self._admit_chains(req)
            if req.trace is not None:
                obs.events.record("admit", trace=req.trace.trace_id,
                                  replica=self.rid,
                                  queue_wait_s=round(wait, 6),
                                  prefix_hit=hit)
            self._cur = (req, enq_t, now,
                         now + (remaining if remaining is not None
                                else self._service_s(req, covered)),
                         covered)

        if now >= self._next_pub:
            self._publish()

        try:
            if (self._stopping and self._cur is None and not self._queue
                    and not self._done_buf
                    and not self.fabric.keys(inbox)):
                # clean drain exit: the lease lapses; the autoscaler's
                # sweep (or the router's drain-departure path) handles
                # the coordination residue, same as a real close
                self.fabric.down(f"{self.ns}:{self.rid}")
                self.alive = False
        except ConnectionError:
            pass   # can't verify an empty inbox blind; close next step


class FleetSim:
    """One offline scenario run (see module docstring).

    ``FleetSim(spec).run()`` returns the scenario summary row —
    the bench-JSONL payload the :class:`~tpudist.sim.scenario.Envelope`
    checks — with ``envelope_ok`` / ``violations`` already folded in."""

    def __init__(self, spec: ScenarioSpec, *,
                 workload: Workload | None = None,
                 service_rates: dict[str, float] | None = None,
                 quantum_s: float = 0.01) -> None:
        self.spec = spec
        self.workload = workload if workload is not None \
            else synthesize(spec)
        self.rates = dict(service_rates or {})
        self.quantum_s = float(quantum_s)
        fleet = spec.fleet
        self.vc = VirtualClock()
        self.fabric = SimFabric(clock=self.vc.monotonic)
        self.ns = f"sim/{spec.name}"
        self.replicas: list[SimReplica] = []
        self._next_rank = 0
        # the declarative FaultScript: brownout windows arm the fabric
        # up front; timed replica faults queue for _advance to fire;
        # a router kill arms the process fault plan at run() time
        self._router_kill_poll: int | None = None
        self._fault_due: list[dict] = []
        for f in getattr(spec, "faults", ()):
            if f["kind"] == "coord_brownout":
                self.fabric.add_outage(f["at_s"],
                                       f["at_s"] + f["for_s"])
            elif f["kind"] == "kill_router":
                self._router_kill_poll = int(f["at_poll"])
            else:
                self._fault_due.append(dict(f))
        self._fault_due.sort(key=lambda f: f["at_s"])
        if int(fleet.get("prefill_replicas") or 0) > 0:
            # disaggregated fleet: two pools instead of a unified one
            for _ in range(int(fleet["prefill_replicas"])):
                self._spawn_one(warmup_s=0.0, role="prefill")
            for _ in range(int(fleet["decode_replicas"])):
                self._spawn_one(warmup_s=0.0, role="decode")
        else:
            for _ in range(int(fleet["replicas"])):
                self._spawn_one(warmup_s=0.0)
        # the alert plane (ISSUE 17): a real TSDB + FleetScraper + the
        # SHIPPED default alert rules, all on the virtual clock and the
        # same fabric the router polls.  Scenario envelopes pin which
        # rules fire per scenario, so the default thresholds become a
        # regression surface instead of folklore.
        self.tsdb = TSDB(retention_s=600.0, resolution_s=0.5,
                         downsample_after_s=120.0,
                         clock=self.vc.monotonic)
        self.alerts = AlertManager(self.tsdb, default_rules(),
                                   clock=self.vc.monotonic)
        self.scraper = FleetScraper(
            self.tsdb, client=self.fabric, namespace=self.ns,
            registry=obs.registry, alerts=self.alerts,
            interval_s=float(fleet["alert_scrape_s"]),
            clock=self.vc.monotonic)
        self._scrape_next = self.scraper.interval_s
        self.router = self._make_router()
        self.scaler: Autoscaler | None = None
        self.scalers: list[Autoscaler] = []
        if fleet.get("autoscale"):
            self.scaler = Autoscaler(
                self.fabric, namespace=self.ns,
                config=AutoscaleConfig(**fleet["autoscale"]),
                spawner=self._spawn_n, clock=self.vc.monotonic)
            self.scalers.append(self.scaler)
        for pool in ("prefill", "decode"):
            # one control loop per pool, each watching only its own
            # replicas' metrics — the live two-Autoscaler deployment
            if fleet.get(f"autoscale_{pool}"):
                self.scalers.append(Autoscaler(
                    self.fabric, namespace=self.ns,
                    config=AutoscaleConfig(**fleet[f"autoscale_{pool}"]),
                    spawner=(lambda n, p=pool: [
                        self._spawn_one(role=p) for _ in range(n)]),
                    pool=pool, clock=self.vc.monotonic))
        self._scaler_next = [s.cfg.poll_s for s in self.scalers]

    @classmethod
    def from_trace(cls, doc: dict, *, name: str = "trace_replay",
                   autoscale: dict | None = None,
                   replicas: int = 1,
                   fleet: dict | None = None,
                   envelope: Envelope | None = None,
                   **kw) -> "FleetSim":
        """A simulator replaying a recorded ``tpudist.events/1``
        document: the trace's enqueue events become the workload
        (:func:`workload_from_trace`) and its ``segment`` ``spt``
        stamps set each replica's service rate
        (:func:`service_rates_from_trace`) — the recorded incident,
        re-run through today's policy code."""
        wl = workload_from_trace(doc, name=name)
        rates = service_rates_from_trace(doc)
        f = {"replicas": replicas, **(fleet or {})}
        if autoscale is not None:
            f["autoscale"] = dict(autoscale)
        spec = ScenarioSpec(
            name=name, duration_s=max(wl.duration_s, 1e-3) + 1.0,
            arrival={"kind": "constant", "rate": 1.0},   # unused: replay
            fleet=f, **({"envelope": envelope} if envelope else {}))
        return cls(spec, workload=wl, service_rates=rates, **kw)

    # -- fleet construction ------------------------------------------------

    def _make_router(self) -> Router:
        # the sim's golden probe: a SimReplica serves ANY request as
        # tokens [0..max_new) with reason "length", so the known-exact
        # answer is range(budget) — deterministic unless the replica is
        # corrupting its commits, which is exactly what a probe tests
        golden = GoldenProbe(prompt=(1, 2, 3, 4),
                             expect=tuple(range(8)), max_new_tokens=8)
        qcfg = QuarantineConfig(
            strike_threshold=3, strike_window_s=30.0,
            probe_interval_s=0.5, probe_timeout_s=10.0,
            reinstate_after=3, retire_after_fails=10)
        return Router(
            self.fabric, namespace=self.ns,
            poll_s=float(self.spec.fleet["router_poll_s"]),
            use_health=False,
            golden_probe=golden, quarantine_config=qcfg,
            alerts=self.alerts,
            clock=self.vc.monotonic, wall=self.vc.wall,
            sleeper=self._advance)

    def _rate_for(self, rid: str) -> float:
        return float(self.rates.get(
            rid, self.rates.get("*",
                                self.spec.fleet["seconds_per_token"])))

    def _spawn_one(self, warmup_s: float | None = None,
                   role: str = "both") -> SimReplica:
        fleet = self.spec.fleet
        rank = self._next_rank
        self._next_rank += 1
        r = SimReplica(
            self.fabric, self.vc, rank=rank, namespace=self.ns,
            role=role,
            seconds_per_token=self._rate_for(f"r{rank}"),
            prefill_s=float(fleet["prefill_s"]),
            prefill_per_token_s=float(fleet["prefill_per_token_s"]),
            warmup_s=(float(fleet["warmup_s"]) if warmup_s is None
                      else warmup_s),
            publish_interval_s=float(fleet["publish_interval_s"]),
            wait_window_s=float(fleet["wait_window_s"]),
            kv_blocks_total=int(fleet.get("kv_blocks_total") or 0),
            prefix_cache_blocks=int(
                fleet.get("prefix_cache_blocks") or 0),
            tier_blocks=int(fleet.get("tier_blocks") or 0),
            preempt=str(fleet.get("preempt") or "degrade"))
        if warmup_s == 0.0:
            r.step()   # live (and publishing) before the first poll
        self.replicas.append(r)
        return r

    def _spawn_n(self, n: int) -> list[SimReplica]:
        """The autoscaler's spawner: joiners warm up for the configured
        virtual seconds before their first heartbeat, reproducing the
        real joiner's compile window."""
        return [self._spawn_one() for _ in range(n)]

    # -- the virtual-time engine -------------------------------------------

    def _advance(self, dt: float) -> None:
        """The router's injected sleeper: advance virtual time in
        quanta, stepping every replica and firing the autoscaler on its
        cadence — the whole world moves while the router 'sleeps'."""
        remaining = float(dt)
        while remaining > 1e-12:
            q = min(self.quantum_s, remaining)
            self.vc.advance(q)
            remaining -= q
            while (self._fault_due
                    and self.vc.monotonic() >= self._fault_due[0]["at_s"]):
                self._fire_fault(self._fault_due.pop(0))
            for r in self.replicas:
                r.step()
            for i, s in enumerate(self.scalers):
                if self.vc.monotonic() >= self._scaler_next[i]:
                    s.poll()
                    self._scaler_next[i] += s.cfg.poll_s
            if self.vc.monotonic() >= self._scrape_next:
                self.scraper.tick(self.vc.monotonic())
                self._scrape_next += self.scraper.interval_s

    def _fire_fault(self, ev: dict) -> None:
        target = next((r for r in self.replicas
                       if r.rid == ev.get("rid") and r.alive), None)
        if target is None:
            return
        if ev["kind"] == "kill_replica":
            target.kill()
        elif ev["kind"] == "drop_heartbeats":
            target.drop_heartbeats(ev["for_s"])
        elif ev["kind"] == "corrupt_replica":
            target.corrupt(every=int(ev.get("every", 1)),
                           count=ev.get("count"))

    # -- one scenario run --------------------------------------------------

    def run(self, *, timeout_s: float | None = None) -> dict:
        """Replay the workload through the real router; returns the
        scenario summary row (bench-JSONL schema, envelope-checked)."""
        # process-global SLO window: scrub the previous scenario's
        # observations so this run's burn gauges start clean
        obs.slo.clear()
        base = _counters_now(self.ns)
        reqs, arrivals = self.workload.requests(self.vc.wall())
        budget = (timeout_s if timeout_s is not None
                  else self.spec.duration_s + 900.0)
        installed = False
        if self._router_kill_poll is not None:
            faults.install(faults.FaultPlan(
                router_kill_after_polls=self._router_kill_poll,
                router_kill_raise=True))
            installed = True
        t0 = time.perf_counter()
        # on_complete is the sim's delivery journal (the results file of
        # the CLI route mode): completions the first router delivered
        # before a crash survive the crash, and recover() is told about
        # them so replayed terminals dedupe instead of double-counting
        comps: list = []
        delivered: list[str] = []

        def _deliver(key, comp):
            comps.append(comp)
            delivered.append(str(comp.rid))

        try:
            try:
                self.router.run(reqs, arrivals=arrivals,
                                timeout_s=budget, on_complete=_deliver)
            except faults.RouterKilled:
                # the injected router crash: a REPLACEMENT router on the
                # same fabric/namespace runs the real journal-recovery
                # path — re-adopting live replicas, sweeping orphans,
                # replaying journaled terminals
                self.router = self._make_router()
                self.router.recover(timeout_s=budget,
                                    delivered=delivered,
                                    on_complete=_deliver)
        finally:
            if installed:
                faults.reset()
        wall_s = time.perf_counter() - t0
        return self._summarize(reqs, comps, base, wall_s)

    def _prefix_hit_rate(self) -> float | None:
        """Fleet-wide offline prefix-cache hit rate: admissions whose
        stamped hash was already in the admitting replica's recent set,
        over all hash-stamped admissions.  ``None`` when the workload
        stamps no hashes (no tenant prefixes) — an envelope bound on an
        unstamped trace would be vacuous, not zero."""
        req_n = sum(r.prefix_requests for r in self.replicas)
        if req_n == 0:
            return None
        hits = sum(r.prefix_hits for r in self.replicas)
        return round(hits / req_n, 4)

    def _global_hit_rate(self) -> float | None:
        """Fleet-wide BLOCK-level KV reuse under the tiered model:
        prompt chain blocks whose pages were already somewhere the
        fleet could reuse them (local HBM, host tier re-admit, or a
        peer pull) over all chain blocks admitted.  ``None`` when no
        replica ran the chain model (``prefix_cache_blocks`` unset) —
        same vacuous-bound discipline as ``prefix_hit_rate``."""
        total = sum(r.chain_blocks_total for r in self.replicas)
        if total == 0:
            return None
        covered = sum(r.chain_blocks_local + r.chain_blocks_tier
                      + r.chain_blocks_pull for r in self.replicas)
        return round(covered / total, 4)

    def _summarize(self, reqs, comps, base: dict, wall_s: float) -> dict:
        spec = self.spec
        reasons: dict[str, int] = {}
        for c in comps:
            reasons[c.reason] = reasons.get(c.reason, 0) + 1
        waits = [w for r in self.replicas for w in r.all_waits]
        ttfts = [t for r in self.replicas for t in r.all_ttfts]
        pwaits = [w for r in self.replicas
                  for w in r.all_waits_priority]
        now = _counters_now(self.ns)
        delta = {k: now.get(k, 0.0) - base.get(k, 0.0) for k in now}

        ups = drains = 0
        ups_by_pool = {"prefill": 0, "decode": 0}
        recovery_s = 0.0
        for scaler in self.scalers:
            for rec in scaler.decision_log:
                if rec["action"] is not None:
                    if rec["action"][0] == "up":
                        ups += 1
                        if scaler.pool in ups_by_pool:
                            ups_by_pool[scaler.pool] += 1
                    else:
                        drains += 1
            breach_ts = [rec["t"] for rec in scaler.decision_log
                         if not rec.get("suppressed")
                         and rec["wait_q"] > scaler.cfg.target_wait_s]
            if breach_ts:
                recovery_s = max(recovery_s,
                                 max(breach_ts) - min(breach_ts)
                                 + scaler.cfg.poll_s)

        row = {
            "scenario": spec.name,
            "requests": len(reqs),
            "lost_requests": len(reqs) - len(comps),
            "completed_ok": (reasons.get("stop", 0)
                             + reasons.get("length", 0)),
            "p99_queue_wait_s": (
                round(float(np.percentile(waits, 99)), 6)
                if waits else 0.0),
            # first-token latency (ISSUE 15): replica-local wait +
            # prompt-pass time, sampled on whichever replica produced
            # the first token — a prefill replica at handoff, a unified
            # replica at its own prompt-pass finish
            "p99_ttft_s": (
                round(float(np.percentile(ttfts, 99)), 6)
                if ttfts else 0.0),
            "recovery_s": round(recovery_s, 3),
            "scale_ups": ups,
            "scale_ups_prefill": ups_by_pool["prefill"],
            "scale_ups_decode": ups_by_pool["decode"],
            "drains": drains,
            "priority_bad": delta.get("slo/bad~class=priority", 0.0),
            "final_replicas": sum(1 for r in self.replicas if r.alive),
            "virtual_s": round(self.vc.monotonic(), 3),
            "sim_wall_s": round(wall_s, 4),
            "speedup": (round(self.vc.monotonic() / wall_s, 1)
                        if wall_s > 0 else None),
            "seed": spec.seed,
            # chaos accounting (ISSUE 12): deaths the router declared,
            # journal recoveries it ran, and the 300s SLO burn — the
            # whole sim finishes in well under 300 real seconds, so
            # this window sees every terminal decision of the run
            "replica_deaths": delta.get("router/replica_deaths", 0.0),
            "router_recoveries": delta.get("router/recoveries", 0.0),
            "burn_rate_300s": round(
                obs.slo.burn_rates().get(300.0, 0.0), 4),
            # data-plane integrity accounting (ISSUE 13): flips the
            # wire checksum caught, quarantine lifecycle counts, and —
            # the one that must stay zero — terminals DELIVERED whose
            # tokens differ from the sim's deterministic service output
            "checksum_mismatches": delta.get(
                "integrity/checksum_mismatch", 0.0),
            "quarantines": delta.get("router/quarantines", 0.0),
            "reinstated": delta.get("router/reinstated", 0.0),
            "retired": delta.get("router/retired", 0.0),
            "probe_pass": delta.get("probe/pass", 0.0),
            "probe_fail": delta.get("probe/fail", 0.0),
            "corrupted_terminals": _corrupted_terminals(reqs, comps),
            # prefix-affinity accounting (ISSUE 14): the fleet-level hit
            # rate the router's hash steer is supposed to preserve under
            # scale-out, plus how many dispatches the steer decided
            "prefix_hit_rate": self._prefix_hit_rate(),
            "prefix_affinity_dispatches": delta.get(
                "router/prefix_affinity", 0.0),
            # tiered-KV accounting (ISSUE 16): block-level reuse across
            # the whole fleet memory hierarchy, its local/tier/pull
            # split, and the pull-mode traffic the router initiated
            "global_hit_rate": self._global_hit_rate(),
            "tier_hit_blocks": sum(r.chain_blocks_tier
                                   for r in self.replicas),
            "pull_hit_blocks": sum(r.chain_blocks_pull
                                   for r in self.replicas),
            "tier_spills": sum(r.tier_spills for r in self.replicas),
            "prefix_pulls": delta.get("router/prefix_pulls", 0.0),
            "prefix_pull_fallbacks": delta.get(
                "router/prefix_pull_fallbacks", 0.0),
            "prefix_stale_skips": delta.get(
                "router/prefix_stale_skips", 0.0),
            # migration accounting (ISSUE 19): preempt/resume volume on
            # the replicas, the PRIORITY class's own queue-wait tail
            # (the number preemption exists to hold down), and the
            # router-side migrate-stage commits and their fallbacks
            "preemptions": delta.get("serve/preempted", 0.0),
            "preempt_resumes": delta.get("serve/resumed", 0.0),
            "p99_priority_wait_s": (
                round(float(np.percentile(pwaits, 99)), 6)
                if pwaits else 0.0),
            "migrations": delta.get("router/migrations", 0.0),
            "migration_fallbacks": delta.get(
                "router/migration_fallbacks", 0.0),
        }
        for reason in ("completed", "shed", "rejected", "failed",
                       "timeout"):
            row[f"decisions_{reason}"] = delta.get(
                f"router/decisions/{reason}", 0.0)
        # alert accounting (ISSUE 17): every rule that reached firing at
        # any point in the run, plus the hash of the rule set it fired
        # under — the envelope's must_fire/must_not_fire checks read
        # these, and bench rows carry the hash for provenance
        row["alerts_fired"] = sorted(self.alerts.fired_names)
        row["alert_rules_hash"] = self.alerts.rules_hash
        violations = spec.envelope.check(row)
        row["envelope_ok"] = not violations
        row["violations"] = violations
        return row


def _corrupted_terminals(reqs, comps) -> int:
    """Delivered completions whose tokens are NOT the sim data plane's
    deterministic output (``range(max_new_tokens)`` with reason
    ``length``) — i.e. corruption that made it past every integrity
    gate to a caller.  The silent_corruption envelope pins this to 0."""
    want = {str(r.rid): int(r.max_new_tokens) for r in reqs}
    bad = 0
    for c in comps:
        if c.reason != "length" or str(c.rid) not in want:
            continue
        if [int(t) for t in np.asarray(c.tokens).tolist()] != list(
                range(want[str(c.rid)])):
            bad += 1
    return bad


def _counters_now(ns: str) -> dict[str, float]:
    """Current values of the process-global counters a scenario summary
    is computed from — summaries are before/after DELTAS because the
    obs registry is cumulative across scenarios in one process."""
    snap = obs.snapshot()
    out: dict[str, float] = {}
    for name, m in snap.get("counters", {}).items():
        if name.startswith(("router/decisions/", "slo/bad", "slo/good",
                            "autoscale/", "router/replica_deaths",
                            "router/recoveries", "coord/",
                            "integrity/", "probe/", "quarantine/",
                            "router/quarantines", "router/reinstated",
                            "router/retired", "router/prefix",
                            "serve/preempted", "serve/resumed",
                            "router/migrations",
                            "router/migration_fallbacks")):
            out[name] = float(m.get("value") or 0.0)
    return out
