"""Workload synthesis and trace replay for the serve fleet.

Two ways to produce the same thing — a time-ordered list of
:class:`WorkItem` (arrival offset + request shape) that both the live
replayer and the offline simulator consume:

* :func:`synthesize` draws one from a :class:`ScenarioSpec` — a
  nonhomogeneous Poisson arrival process (thinning against the
  scenario's rate curve: constant, diurnal sinusoid, flash-crowd
  spike), prompt lengths (uniform or long-tail), token budgets,
  deadline distributions (including the adversarial tight/loose mix),
  and a weighted tenant mix whose shared system prefixes reproduce the
  prefix-cache-shaped traffic real fleets see.  Deterministic under
  ``spec.seed``.
* :func:`workload_from_trace` reconstructs one from a recorded
  ``tpudist.events/1`` document: the router's ``enqueue`` events carry
  ``prompt_tokens`` / ``max_new`` / ``priority`` / ``rel_deadline_s``
  exactly so a production incident's arrival pattern can be replayed —
  against the live fleet or the offline simulator — as a regression
  scenario.

:meth:`Workload.requests` materializes real
:class:`tpudist.models.serving.Request` objects plus the ``arrivals``
offset list ``Router.run(..., arrivals=...)`` expects, so the SAME
workload drives the SAME router code on both execution paths.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from tpudist.sim.scenario import ScenarioSpec

__all__ = ["WorkItem", "Workload", "synthesize", "workload_from_trace",
           "service_rates_from_trace"]

# synthesized prompts draw token ids from this range; the tiny fleet
# models all have vocab >= 64 and the simulator never embeds them
_VOCAB = 64


@dataclass(frozen=True)
class WorkItem:
    """One arrival: WHEN (offset seconds from workload start) and WHAT
    (request shape).  ``prefix_tokens`` > 0 marks the leading span of
    the prompt as the tenant's shared system prefix."""

    at: float
    prompt_tokens: int
    max_new: int
    rel_deadline_s: float | None = None
    priority: int = 0
    tenant: str | None = None
    prefix_tokens: int = 0


@dataclass(frozen=True)
class Workload:
    """A materializable arrival schedule (see module docstring)."""

    items: tuple[WorkItem, ...]
    name: str = "workload"
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(
            self, "items",
            tuple(sorted(self.items, key=lambda w: w.at)))

    def __len__(self) -> int:
        return len(self.items)

    @property
    def duration_s(self) -> float:
        return self.items[-1].at if self.items else 0.0

    def requests(self, base_wall: float):
        """(requests, arrivals) for ``Router.run``: real ``Request``
        objects whose absolute ``deadline_s`` is anchored at
        ``base_wall + at + rel_deadline`` (pass the clock the router
        will read — ``time.time()`` live, ``VirtualClock.wall()``
        simulated), plus the matching arrival-offset list."""
        from tpudist.models.kv_pages import request_prefix_hash
        from tpudist.models.serving import Request

        rng = np.random.default_rng(self.seed ^ 0x5EED)
        # one stable shared prefix per tenant: the whole tenant's
        # traffic opens with the same token span (prefix-cache shape)
        prefixes: dict[str, np.ndarray] = {}
        reqs, arrivals = [], []
        for n, w in enumerate(self.items):
            pre = np.zeros((0,), np.int32)
            if w.tenant is not None and w.prefix_tokens > 0:
                if w.tenant not in prefixes:
                    trng = np.random.default_rng(
                        (self.seed << 8) ^ hash(w.tenant) & 0xFFFF)
                    prefixes[w.tenant] = trng.integers(
                        1, _VOCAB, size=w.prefix_tokens).astype(np.int32)
                pre = prefixes[w.tenant]
            tail_n = max(1, w.prompt_tokens - pre.size)
            prompt = np.concatenate(
                [pre, rng.integers(1, _VOCAB, size=tail_n).astype(np.int32)])
            deadline = None if w.rel_deadline_s is None else \
                base_wall + w.at + w.rel_deadline_s
            # the tenant's shared system prefix, stamped as the opaque
            # affinity hash the router's prefix steering matches on (and
            # FleetSim's offline hit-rate accounting counts)
            phash = (request_prefix_hash(pre) if pre.size else None)
            reqs.append(Request(
                prompt=prompt, max_new_tokens=int(w.max_new),
                rid=f"{self.name}-{n:05d}", deadline_s=deadline,
                priority=int(w.priority), prefix_hash=phash))
            arrivals.append(float(w.at))
        return reqs, arrivals


# -- arrival processes ------------------------------------------------------

def _rate_fn(arrival: dict):
    """(rate(t), rate_max) for the scenario's arrival law — the inputs
    Lewis-Shedler thinning needs."""
    kind = arrival["kind"]
    if kind == "constant":
        r = float(arrival["rate"])
        return (lambda t: r), r
    if kind == "diurnal":
        base = float(arrival["base_rate"])
        peak = float(arrival["peak_rate"])
        period = float(arrival["period_s"])
        mid, amp = (base + peak) / 2.0, (peak - base) / 2.0

        def rate(t: float) -> float:
            # trough at t=0, peak at period/2: a compressed day
            return mid - amp * math.cos(2.0 * math.pi * t / period)
        return rate, peak
    if kind == "flash_crowd":
        base = float(arrival["base_rate"])
        spike = float(arrival["spike_rate"])
        at = float(arrival.get("spike_at_s", 0.0))
        width = float(arrival["spike_width_s"])

        def rate(t: float) -> float:
            return spike if at <= t < at + width else base
        return rate, spike
    raise ValueError(f"unknown arrival kind {kind!r}")


def _thin_arrivals(arrival: dict, duration_s: float,
                   rng: np.random.Generator) -> list[float]:
    """Nonhomogeneous Poisson arrival times on [0, duration) by
    thinning: candidates at the max rate, kept with probability
    rate(t)/rate_max."""
    rate, rate_max = _rate_fn(arrival)
    out: list[float] = []
    t = 0.0
    while True:
        t += rng.exponential(1.0 / rate_max)
        if t >= duration_s:
            return out
        if rng.random() * rate_max <= rate(t):
            out.append(t)


def _draw_prompt(prompt: dict, rng: np.random.Generator) -> int:
    if prompt["kind"] == "uniform":
        return int(rng.integers(prompt["lo"], prompt["hi"] + 1))
    # longtail: mostly short (lo..typical), a tail_frac slice drawn
    # log-uniform out to `tail` — the mixed-context-length reality that
    # stresses queueing behind long prefills
    if rng.random() < float(prompt.get("tail_frac", 0.05)):
        lo, hi = math.log(prompt["typical"]), math.log(prompt["tail"])
        return int(round(math.exp(rng.uniform(lo, hi))))
    return int(rng.integers(prompt["lo"], prompt["typical"] + 1))


def _draw_max_new(max_new: dict, rng: np.random.Generator) -> int:
    if max_new["kind"] == "const":
        return int(max_new["value"])
    return int(rng.integers(max_new["lo"], max_new["hi"] + 1))


def _draw_deadline(deadline: dict,
                   rng: np.random.Generator) -> float | None:
    kind = deadline["kind"]
    if kind == "none":
        return None
    if kind == "uniform":
        return float(rng.uniform(deadline["lo"], deadline["hi"]))
    # adversarial: a tight_frac slice gets near-impossible deadlines
    # (exercising shed/timeout-at-admission), the rest are loose
    if rng.random() < float(deadline["tight_frac"]):
        return float(deadline["tight_s"])
    return float(deadline["loose_s"])


def _pick_tenant(tenants: tuple, rng: np.random.Generator) -> dict | None:
    if not tenants:
        return None
    weights = np.asarray([float(t["weight"]) for t in tenants])
    idx = rng.choice(len(tenants), p=weights / weights.sum())
    return tenants[int(idx)]


def synthesize(spec: ScenarioSpec) -> Workload:
    """Draw the scenario's workload (deterministic under ``spec.seed``)."""
    rng = np.random.default_rng(spec.seed)
    items = []
    for at in _thin_arrivals(spec.arrival, spec.duration_s, rng):
        tenant = _pick_tenant(spec.tenants, rng)
        items.append(WorkItem(
            at=round(at, 6),
            prompt_tokens=_draw_prompt(spec.prompt, rng),
            max_new=_draw_max_new(spec.max_new, rng),
            rel_deadline_s=_draw_deadline(spec.deadline, rng),
            priority=int(tenant.get("priority", 0)) if tenant else 0,
            tenant=tenant["name"] if tenant else None,
            prefix_tokens=int(tenant.get("prefix_tokens", 0))
            if tenant else 0))
    return Workload(items=tuple(items), name=spec.name, seed=spec.seed)


# -- trace replay -----------------------------------------------------------

def workload_from_trace(doc: dict, name: str = "trace-replay") -> Workload:
    """A replayable workload from a recorded ``tpudist.events/1``
    document: every router ``enqueue`` event becomes a :class:`WorkItem`
    at its original offset from the first arrival, with the original
    prompt length, token budget, priority, and RELATIVE deadline — the
    incident's arrival pattern, detached from its wall clock."""
    enq = [e for e in doc.get("events", [])
           if e.get("kind") == "enqueue" and "prompt_tokens" in e]
    if not enq:
        raise ValueError(
            "trace has no replayable enqueue events (need "
            "prompt_tokens/max_new fields — recorded by Router._arrive)")
    t0 = min(e["t"] for e in enq)
    items = [WorkItem(
        at=round(float(e["t"]) - t0, 6),
        prompt_tokens=int(e["prompt_tokens"]),
        max_new=int(e["max_new"]),
        rel_deadline_s=e.get("rel_deadline_s"),
        priority=int(e.get("priority", 0) or 0)) for e in enq]
    return Workload(items=tuple(items), name=name)


def service_rates_from_trace(doc: dict,
                             default: float = 0.002) -> dict[str, float]:
    """Per-replica seconds-per-token from a recorded trace: the median
    of each source's ``segment`` events' ``spt`` stamps (the ServeLoop's
    realized-rate EMA at that moment).  This is what makes the offline
    simulator's replicas serve at the RECORDED fleet's pace.  Sources
    with no stamped segments fall back to ``default`` (also the return
    value's ``"*"`` entry, for replicas the autoscaler spawns that the
    trace never saw)."""
    by_src: dict[str, list[float]] = {}
    for e in doc.get("events", []):
        if e.get("kind") == "segment" and e.get("spt"):
            by_src.setdefault(str(e.get("src", "?")), []).append(
                float(e["spt"]))
    out = {"*": float(default)}
    for src, vals in by_src.items():
        out[src] = float(np.median(vals))
    return out
