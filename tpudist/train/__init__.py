"""Training drivers (the reference's L4 layer, SURVEY.md §1)."""

from tpudist.train.state import TrainState
from tpudist.train.trainer import Trainer, TrainerConfig

__all__ = ["TrainState", "Trainer", "TrainerConfig"]
