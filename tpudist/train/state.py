"""Train state: the full pytree that defines a training run.

Deliberately exceeds the reference's snapshot fidelity (SURVEY.md §5
"Checkpoint / resume": the reference saves only MODEL_STATE + EPOCHS_RUN,
`mnist_ddp_elastic.py:99-102`, losing optimizer state and RNG): tpudist's
state carries params, optimizer state, step counter and the PRNG key, so a
restore is a bitwise continuation.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    rng: jax.Array
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    @classmethod
    def create(
        cls,
        apply_fn: Callable,
        params: Any,
        tx: optax.GradientTransformation,
        rng: jax.Array | int = 0,
    ) -> "TrainState":
        if isinstance(rng, int):
            rng = jax.random.key(rng)
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=rng,
            apply_fn=apply_fn,
            tx=tx,
        )

    @classmethod
    def create_sharded(
        cls,
        apply_fn: Callable,
        params: Any,
        tx: optax.GradientTransformation,
        spec: Any,
        mesh: Any = None,
        rng: jax.Array | int = 0,
    ) -> "TrainState":
        """Create a state laid out by a :class:`tpudist.parallel.mesh.
        MeshSpec`: params sharded by the spec's composed rules
        (tp/ep rules first, fsdp takes a remaining dim), optimizer state
        inheriting the shardings.  ``mesh`` defaults to ``spec.build()``.
        Lazy import — the parallel layer depends on this module."""
        from tpudist.parallel.mesh import make_composed_state

        if mesh is None:
            mesh = spec.build()
        state, _ = make_composed_state(apply_fn, params, tx, spec, mesh,
                                       rng=rng)
        return state

    def apply_gradients(self, grads: Any) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        new_rng, _ = jax.random.split(self.rng)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            rng=new_rng,
        )
