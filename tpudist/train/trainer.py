"""Trainer — the DDP elastic ``Trainer`` twin (`mnist_ddp_elastic.py:30-130`).

Same surface (snapshot load on start, per-epoch train + test, periodic
snapshot save), TPU-native internals: the model is not "wrapped in DDP" —
the train step is SPMD over the mesh's data axis with an explicit grad
``pmean`` (see :mod:`tpudist.parallel.data_parallel`).

Deliberate upgrades over the reference, each flagged in SURVEY.md:
* snapshots carry optimizer state + RNG + step, so resume is exact
  (reference saves only MODEL_STATE/EPOCHS_RUN, `mnist_ddp_elastic.py:99-102`);
* only the coordinator process writes snapshots (the reference's
  ``local_rank == 0`` gate writes once *per node*, `mnist_ddp_elastic.py:113`);
* evaluation psums exact correct-counts instead of per-rank prints.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import time

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from tpudist import obs
from tpudist.obs import xla as obs_xla
from tpudist.data.device_prefetch import device_prefetch
from tpudist.data.loader import ShardedLoader
from tpudist.elastic.checkpoint import Checkpointer, restore_pytree
from tpudist.ops.losses import cross_entropy
from tpudist.parallel.data_parallel import (
    broadcast_params,
    make_dp_masked_eval_step,
    make_dp_train_loop,
    make_dp_train_step,
)
from tpudist.parallel.mesh import (
    MeshSpec,
    make_composed_eval_step,
    make_composed_state,
    make_composed_train_step,
)
from tpudist.train.state import TrainState
from tpudist.utils.config import config_field
from tpudist.utils.logging import get_logger
from tpudist.utils.metrics import MetricLogger, ThroughputMeter, maybe_profile

log = get_logger(__name__)


@dataclasses.dataclass
class TrainerConfig:
    """CLI-overridable twin of the reference's argparse surface
    (`mnist_ddp_elastic.py:203-208`)."""

    total_epochs: int = config_field(5, "epochs to train")
    save_every: int = config_field(1, "snapshot period in epochs")
    batch_size: int = config_field(128, "GLOBAL batch size (reference default 128)")
    snapshot_path: str = config_field("snapshot.npz", "snapshot file")
    log_every: int = config_field(50, "log every N steps")
    eval_every_epoch: bool = config_field(True, "run test() after every epoch")
    profile_dir: str = config_field(
        "", "write a jax.profiler trace of epoch 0 here (XProf/TensorBoard)"
    )
    steps_per_dispatch: int = config_field(
        1,
        "optimizer steps fused per device dispatch (lax.scan); >1 keeps "
        "small models compute-bound instead of dispatch-bound, numerics "
        "identical to stepwise",
    )
    device_prefetch: int = config_field(
        2,
        "train batches whose host->device transfers are kept in flight "
        "ahead of the step (tpudist.data.device_prefetch); 0 = pull "
        "batches synchronously; numerics identical either way",
    )
    async_snapshot: bool = config_field(
        True,
        "snapshot saves block only to initiate device-side copies; d2h "
        "and the disk write overlap the next epoch (Checkpointer "
        "async_save); False restores fully synchronous saves",
    )
    mesh_axes: str = config_field(
        "",
        "composed-mesh axis sizes, e.g. 'dp=2,fsdp=2,tp=2' — selects HOW "
        "the model trains by axis size instead of strategy function "
        "(tpudist.parallel.mesh.MeshSpec); empty keeps the legacy "
        "data-parallel path over the provided mesh",
    )


class Trainer:
    def __init__(
        self,
        config: TrainerConfig,
        model_apply: Callable,
        params: Any,
        tx: optax.GradientTransformation,
        mesh: Mesh | MeshSpec,
        train_loader: ShardedLoader,
        test_loader: ShardedLoader | None = None,
        loss_fn: Callable = cross_entropy,
        train_kwargs: dict | None = None,
        seed: int = 0,
    ) -> None:
        self.config = config
        # One declarative knob for HOW the model trains: a MeshSpec (passed
        # directly or parsed from config.mesh_axes) selects axis sizes; the
        # strategy follows from them (make_composed_train_step).  No spec =
        # the legacy data-parallel path over the provided mesh, unchanged.
        self.mesh_spec: MeshSpec | None = None
        if isinstance(mesh, MeshSpec):
            self.mesh_spec = mesh
            mesh = mesh.build()
        elif config.mesh_axes:
            self.mesh_spec = MeshSpec.parse(config.mesh_axes)
            for name, size in self.mesh_spec.axis_sizes().items():
                if mesh.shape.get(name) != size:
                    raise ValueError(
                        f"config.mesh_axes={config.mesh_axes!r} wants axis "
                        f"{name}={size} but the provided mesh has "
                        f"{dict(mesh.shape)}; build it with "
                        "MeshSpec.parse(config.mesh_axes).build()")
        if self.mesh_spec is not None and self.mesh_spec.pp > 1:
            raise ValueError(
                "Trainer's epoch/eval/snapshot loop assumes a "
                "(state, inputs, labels) step; pipeline (pp > 1) training "
                "uses stage-stacked params and a schedule-specific batch "
                "layout — drive make_composed_train_step directly (see "
                "tpudist/parallel/mesh_bench.py)")
        self.mesh = mesh
        self.train_loader = train_loader
        self.test_loader = test_loader
        self.epochs_run = 0
        train_kwargs = train_kwargs or {}
        if config.batch_size != train_loader.global_batch:
            raise ValueError(
                f"TrainerConfig.batch_size={config.batch_size} does not match "
                f"train_loader.global_batch={train_loader.global_batch}; the "
                "config value is the single source of truth for the CLI surface"
            )

        def dp_loss(params, batch, rng):
            inputs, labels = batch
            logits = model_apply(
                {"params": params}, inputs, rngs={"dropout": rng}, **train_kwargs
            )
            return loss_fn(logits, labels), {}

        def dp_predict(params, inputs):
            return model_apply({"params": params}, *inputs)

        spec = self.mesh_spec
        if spec is None:
            self.state = TrainState.create(
                apply_fn=model_apply,
                params=broadcast_params(params, mesh),
                tx=tx,
                rng=jax.random.key(seed),
            )
        else:
            self.state, self._param_specs = make_composed_state(
                model_apply, params, tx, spec, mesh,
                rng=jax.random.key(seed))
        # ONE save path shared with the elastic runtime: the flat layout
        # keeps the reference's rolling snapshot.npz contract while async
        # saves overlap d2h + disk write with the next epoch's compute
        self._ckpt = Checkpointer(config.snapshot_path,
                                  async_save=config.async_snapshot,
                                  layout="flat")
        self._maybe_load_snapshot()
        if spec is None:
            self.train_step = make_dp_train_step(dp_loss, mesh)
            self.train_loop = (
                make_dp_train_loop(dp_loss, mesh)
                if config.steps_per_dispatch > 1 else None
            )
            self.eval_step = make_dp_masked_eval_step(dp_predict, mesh)
        else:
            pure_dp = spec.fsdp == spec.tp == spec.ep == 1
            self.train_step = make_composed_train_step(
                spec, mesh, dp_loss, params=self.state.params)
            if config.steps_per_dispatch > 1:
                if not pure_dp:
                    raise ValueError(
                        "steps_per_dispatch > 1 (the fused dp scan loop) "
                        "is data-parallel only; set it to 1 for "
                        "fsdp/tp/ep specs")
                self.train_loop = make_dp_train_loop(dp_loss, mesh,
                                                     axis="dp")
            else:
                self.train_loop = None
            self.eval_step = make_composed_eval_step(dp_predict, mesh)
        self.metrics = MetricLogger()
        self.throughput = ThroughputMeter(warmup_steps=2)
        # obs handles cached once: the hot loop touches them by attribute,
        # not by registry lookup.  Recording stays lazy — the loss gauge
        # takes the device array as-is; counters take host ints; the
        # step-time histogram takes host floats — so nothing here adds a
        # sync to the step path (snapshot() pays the one batched sync).
        self._obs_steps = obs.counter("train/steps", unit="steps")
        self._obs_examples = obs.counter("train/examples", unit="examples")
        self._obs_epochs = obs.counter("train/epochs", unit="epochs")
        self._obs_loss = obs.gauge("train/loss")
        self._obs_tput = obs.gauge("train/images_per_sec", unit="img/s")
        self._obs_step_time = obs.histogram("train/step_time", unit="s")
        # per-optimizer-step program FLOPs, filled by the one-time cost
        # probe on the first dispatch; feeds the live MFU gauge
        self._step_flops: float | None = None
        self._cost_probed = False

    def _probe_cost(self, fn, steps_per_dispatch: int, *args) -> None:
        """One-time lower() of the step program: cost_analysis() FLOPs for
        the live ``xla/mfu`` gauge, HLO text for the flight recorder's
        post-mortem bundle.  Pure analysis — no compile, no dispatch."""
        if self._cost_probed:
            return
        self._cost_probed = True
        lower = getattr(fn, "lower", None)
        if lower is None:
            return
        try:
            with obs.span("cost_probe"):
                lowered = lower(self.state, *args)
            flops = obs_xla.cost_flops(lowered)
            if flops is not None:
                self._step_flops = flops / steps_per_dispatch
            try:
                hlo = lowered.as_text(dialect="hlo")
            except Exception:  # noqa: BLE001 - dialect arg may vanish
                hlo = lowered.as_text()
            obs.recorder.note_hlo(hlo)
        except Exception as e:  # noqa: BLE001 - telemetry must not stop training
            log.debug("cost probe failed: %s", e)

    # -- snapshotting (`_save_snapshot`/`_load_snapshot` parity, with full state)

    def _maybe_load_snapshot(self) -> None:
        import os

        if os.path.exists(self.config.snapshot_path):
            tree, meta = restore_pytree(
                self.config.snapshot_path,
                {
                    "params": self.state.params,
                    "opt_state": self.state.opt_state,
                    "rng": self.state.rng,
                },
            )
            if self.mesh_spec is None:
                params = broadcast_params(tree["params"], self.mesh)
                opt_state = broadcast_params(tree["opt_state"], self.mesh)
            else:
                # restore into the composed layout: every leaf goes back
                # where its live counterpart lives (fsdp/tp/ep shards
                # included), not broadcast-replicated
                place = lambda new, like: jax.device_put(new, like.sharding)
                params = jax.tree.map(place, tree["params"],
                                      self.state.params)
                opt_state = jax.tree.map(place, tree["opt_state"],
                                         self.state.opt_state)
            self.state = self.state.replace(
                params=params,
                opt_state=opt_state,
                rng=tree["rng"],
                step=jnp.asarray(meta.get("step", 0), jnp.int32),
            )
            self.epochs_run = int(meta.get("epochs_run", 0))
            log.info("Resuming from snapshot at epoch %d", self.epochs_run)

    def _save_snapshot(self, epoch: int) -> None:
        if jax.process_index() != 0:
            return
        # step stays a DEVICE scalar: Checkpointer stages an on-device
        # copy at initiation (so the next epoch's donating dispatch can't
        # delete it) and resolves it on the writer thread — the epoch
        # boundary never syncs on it
        with obs.span("snapshot_save", epoch=epoch):
            self._ckpt.save(
                epoch,
                {
                    "params": self.state.params,
                    "opt_state": self.state.opt_state,
                    "rng": self.state.rng,
                },
                meta={"epochs_run": epoch + 1, "step": self.state.step},
            )
        log.info("Epoch %d | snapshot save initiated to %s (async=%s)",
                 epoch, self.config.snapshot_path, self._ckpt.async_save)

    # -- the hot loop (`_run_epoch`/`_run_batch` parity)

    def _feed(self, batches):
        """Device-input pipelining for the hot loop: keep
        ``config.device_prefetch`` batches' transfers in flight ahead of
        the step (0 = plain synchronous pull).  Skipped when the loader's
        Python-thread fallback already drives the stream ahead
        (``ShardedLoader.thread_prefetch``): one prefetch layer only —
        wrapping twice would double the buffered batches and the worker
        threads.  Consumer stalls surface as the ``data/input_stall``
        counter."""
        if (self.config.device_prefetch > 0
                and not self.train_loader.thread_prefetch):
            return device_prefetch(batches, depth=self.config.device_prefetch)
        return batches

    def _run_epoch(self, epoch: int) -> dict:
        self.throughput.start()
        n = self.config.steps_per_dispatch
        start_step = 0
        if self.train_loop is not None:
            # Fused path: n optimizer steps per compiled dispatch.
            groups = self.train_loader.stacked_groups(n)
            start_step = groups * n
            for g, batch in enumerate(
                    self._feed(self.train_loader.epoch_stacked(epoch, n))):
                self._probe_cost(self.train_loop, n, *batch)
                t0 = time.perf_counter()
                with obs.span("train_dispatch", steps=n):
                    self.state, metrics = self.train_loop(self.state, *batch)
                # stacked [n] metrics accumulate lazily; MetricLogger
                # weights every optimizer step equally
                self.metrics.update(**metrics)
                self.throughput.step(n * self.train_loader.global_batch)
                # the loss gauge keeps the stacked DEVICE array; its last
                # element is folded out at snapshot time, never here
                self._obs_loss.set(metrics["loss"])
                self._obs_steps.inc(n)
                self._obs_examples.inc(n * self.train_loader.global_batch)
                self._obs_step_time.record((time.perf_counter() - t0) / n)
                if (g * n) % self.config.log_every < n:
                    loss = float(metrics["loss"][-1])
                    log.info("epoch %d step %d loss %.4f", epoch,
                             g * n + n - 1, loss)
                    # flight-recorder breadcrumb at log granularity: the
                    # loss is already on host here, so this adds no sync
                    obs.recorder.record("train_log", epoch=epoch,
                                        step=g * n + n - 1, loss=loss)
        for step, batch in enumerate(
                self._feed(self.train_loader.epoch(epoch,
                                                   start_step=start_step)),
                start=start_step):
            self._probe_cost(self.train_step, 1, *batch)
            t0 = time.perf_counter()
            with obs.span("train_step", step=step):
                self.state, metrics = self.train_step(self.state, *batch)
            # device scalars accumulate lazily; the host sync happens once per
            # epoch (and at log points), not per step
            self.metrics.update(**metrics)
            self.throughput.step(self.train_loader.global_batch)
            self._obs_loss.set(metrics["loss"])
            self._obs_steps.inc()
            self._obs_examples.inc(self.train_loader.global_batch)
            # dispatch time unless TPUDIST_OBS_FENCE=1 makes spans fence
            self._obs_step_time.record(time.perf_counter() - t0)
            if step % self.config.log_every == 0:
                loss = float(metrics["loss"])
                log.info("epoch %d step %d loss %.4f", epoch, step, loss)
                obs.recorder.record("train_log", epoch=epoch, step=step,
                                    loss=loss)
        return self.metrics.reset()

    def train(self, max_epochs: int | None = None) -> dict:
        # any unhandled exception dumps a post-mortem bundle (event ring,
        # final snapshot, HLO) before propagating
        with obs.recorder.guard("trainer", epochs_run=self.epochs_run):
            return self._train(max_epochs)

    def _train(self, max_epochs: int | None = None) -> dict:
        max_epochs = max_epochs or self.config.total_epochs
        summary: dict = {}
        start_epoch = self.epochs_run
        for epoch in range(start_epoch, max_epochs):
            profiling = self.config.profile_dir and epoch == start_epoch
            with maybe_profile(self.config.profile_dir if profiling else None):
                # obs spans nest inside the XProf trace (TraceAnnotation)
                with obs.span("train_epoch", epoch=epoch):
                    epoch_metrics = self._run_epoch(epoch)
            self._obs_epochs.inc()
            self._obs_tput.set(self.throughput.items_per_sec)
            # live efficiency gauges, refreshed per epoch: MFU from the
            # cost probe's FLOPs over the measured mean step time, and
            # the per-device HBM stats (no-ops off-TPU)
            obs_xla.note_step(self.throughput.mean_step_time,
                              self._step_flops)
            obs_xla.update_memory_gauges()
            summary = {"epoch": epoch, **epoch_metrics}
            obs.recorder.record(
                "epoch_end", epoch=epoch,
                loss=epoch_metrics.get("loss"),
                images_per_sec=round(self.throughput.items_per_sec, 2))
            if self.config.eval_every_epoch and self.test_loader is not None:
                summary["test_accuracy"] = self.test()
                log.info(
                    "epoch %d done | loss %.4f | test acc %.2f%%",
                    epoch, epoch_metrics.get("loss", float("nan")),
                    100 * summary["test_accuracy"],
                )
            if epoch % self.config.save_every == 0:
                self._save_snapshot(epoch)
            self.epochs_run = epoch + 1
        # join the in-flight async snapshot write: train() returning
        # means the last snapshot is durable on disk
        self._ckpt.wait()
        summary["images_per_sec"] = self.throughput.items_per_sec
        return summary

    def test(self) -> float:
        """Exact test accuracy: each real sample counted once — the
        validity mask zeroes wrap-around padding from ``drop_last=False``
        sharding, and the denominator is the true number of evaluated
        samples, not batches × batch-size (the reference divides by the
        padded sampler length, `mnist_ddp_elastic.py:117-130`)."""
        assert self.test_loader is not None
        correct: list = []
        seen: list = []
        for step, batch in enumerate(self.test_loader.epoch(0)):
            mask = self.test_loader.valid_mask(step)
            c, t = self.eval_step(self.state.params, *batch, mask)
            # accumulate DEVICE scalars; steps chain async without the
            # two per-step host syncs the reference-era loop paid
            correct.append(c)
            seen.append(t)
        if not seen:
            return 0.0
        cs, ts = jax.device_get((correct, seen))  # ONE sync per evaluation
        return int(sum(int(x) for x in cs)) / max(int(sum(int(x) for x in ts)), 1)
