"""Utilities: config, logging, metrics, pytree helpers."""

from tpudist.utils.config import cli_override, config_field, make_parser
from tpudist.utils.logging import get_logger
from tpudist.utils.metrics import MetricLogger, Stopwatch, ThroughputMeter

__all__ = [
    "MetricLogger",
    "Stopwatch",
    "ThroughputMeter",
    "cli_override",
    "config_field",
    "get_logger",
    "make_parser",
]
