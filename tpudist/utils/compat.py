"""JAX version compatibility shims.

tpudist targets the current jax API surface; this module backfills the
handful of renamed symbols so the same code runs on the older runtimes
some images ship (observed: jax 0.4.37).  Installed once from
``tpudist/__init__`` before any kernel or parallel module loads:

* ``jax.shard_map`` — promoted from ``jax.experimental.shard_map`` in
  newer jax; the old entry point also spells ``check_vma`` as
  ``check_rep``, so the shim renames that kwarg.
* ``jax.experimental.pallas.tpu.CompilerParams`` — the old name is
  ``TPUCompilerParams`` (same dataclass fields).
* ``jax.tree.leaves_with_path`` — old home:
  ``jax.tree_util.tree_leaves_with_path``.
* ``jax.lax.axis_size`` — on old jax the static mapped-axis size is what
  ``jax.core.axis_frame(name)`` returns.

Each shim is installed only when the modern name is missing, so on a
current jax this module is a no-op.  Installation failures are swallowed:
a partially available jax (or none at all, for host-only tools) must not
break ``import tpudist``.
"""

from __future__ import annotations

import functools

__all__ = ["install_jax_compat"]


def install_jax_compat() -> None:
    try:
        import jax
    except Exception:
        return

    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map as _shard_map

            @functools.wraps(_shard_map)
            def shard_map(f, *args, **kwargs):
                if "check_vma" in kwargs:
                    kwargs["check_rep"] = kwargs.pop("check_vma")
                return _shard_map(f, *args, **kwargs)

            jax.shard_map = shard_map
        except Exception:
            pass

    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams"):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except Exception:
        pass

    if not hasattr(jax.lax, "axis_size"):
        try:
            from jax import core as _core

            def axis_size(axis_name):
                if isinstance(axis_name, (tuple, list)):
                    n = 1
                    for a in axis_name:
                        n *= _core.axis_frame(a)
                    return n
                return _core.axis_frame(axis_name)

            jax.lax.axis_size = axis_size
        except Exception:
            pass

    try:
        if not hasattr(jax.tree, "leaves_with_path"):
            from jax import tree_util

            jax.tree.leaves_with_path = tree_util.tree_leaves_with_path
    except Exception:
        pass
