"""Dataclass-based configuration with CLI override.

The reference configures workloads with a mix of argparse (exactly one script,
`mnist_ddp_elastic.py:203-208`) and module-level constants (SURVEY.md §5
"Config / flag system").  Here every workload gets one dataclass config and a
generated CLI: any field can be overridden with ``--field value``.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Any, Sequence, Type, TypeVar

T = TypeVar("T")

_FALSY = ("", "0", "false", "no", "off")


def env_flag(name: str, default: bool = False) -> bool:
    """Parse a boolean environment variable the way users expect: unset
    (or empty) means ``default``; ``0`` / ``false`` / ``no`` / ``off``
    (any case) mean False; anything else means True.  The shared parser
    for every TPUDIST_* knob — ``bool(os.environ.get(...))`` treats
    ``=0`` as *enabled*, which is how `TPUDIST_DISABLE_HEAD_PAIRING=0`
    used to disable head pairing."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in _FALSY


def config_field(default: Any, help: str = "") -> Any:  # noqa: A002 - argparse parlance
    return dataclasses.field(default=default, metadata={"help": help})


def _add_field_arg(parser: argparse.ArgumentParser, f: dataclasses.Field) -> None:
    name = "--" + f.name.replace("_", "-")
    help_text = f.metadata.get("help", "")
    if f.type in ("bool", bool) or isinstance(f.default, bool):
        parser.add_argument(
            name,
            type=lambda s: s.lower() in ("1", "true", "yes"),
            default=f.default,
            help=f"{help_text} (default: {f.default})",
        )
    else:
        typ = type(f.default) if f.default is not None else str
        parser.add_argument(
            name, type=typ, default=f.default, help=f"{help_text} (default: {f.default})"
        )


def make_parser(config_cls: Type[T], description: str = "") -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=description)
    for f in dataclasses.fields(config_cls):
        if f.metadata.get("cli", True):
            _add_field_arg(parser, f)
    return parser


def cli_override(config_cls: Type[T], argv: Sequence[str] | None = None, description: str = "") -> T:
    """Parse ``argv`` into an instance of ``config_cls``."""
    parser = make_parser(config_cls, description)
    ns = parser.parse_args(argv)
    names = {f.name for f in dataclasses.fields(config_cls)}
    return config_cls(**{k: v for k, v in vars(ns).items() if k in names})
