"""Rank-stamped logging.

The reference's observability is bare ``print`` with manual rank prefixes
(`mnist_ddp_elastic.py:88`, `horovod_mnist_elastic.py:73` — SURVEY.md §5).
Here: standard :mod:`logging` with a ``[pN]`` process stamp.

The process index is resolved *lazily at emission time, and only if a JAX
backend already exists* — calling ``jax.process_index()`` eagerly would
initialize the backend as an import side effect (and on TPU that means
touching the runtime before the trainer decides how), so loggers must never
be the first thing that talks to the hardware.
"""

from __future__ import annotations

import logging
import sys

_configured = False


def _process_index_if_initialized() -> int:
    """Process index without forcing backend initialization."""
    try:
        from jax._src import xla_bridge as xb

        if getattr(xb, "_backends", None):
            import jax

            return jax.process_index()
    except Exception:
        pass
    return 0


class _RankFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        record.rank = _process_index_if_initialized()
        return super().format(record)


def get_logger(name: str = "tpudist") -> logging.Logger:
    global _configured
    logger = logging.getLogger(name)
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            _RankFormatter(
                fmt="%(asctime)s [p%(rank)s] %(name)s %(levelname)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        root = logging.getLogger("tpudist")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True
    return logger


def coordinator_only(logger: logging.Logger) -> logging.Logger:
    """Silence INFO output on non-coordinator processes (call after the
    backend is up, e.g. from a trainer) to avoid N-way duplicated logs."""
    if _process_index_if_initialized() != 0:
        logger.setLevel(logging.WARNING)
    return logger
