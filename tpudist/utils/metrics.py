"""Step-time / throughput metrics.

Strictly more than the reference's perf signal (end-to-end ``time.time()``
deltas, `mnist_ddp_elastic.py:210-213`, `model_parallel_ResNet50.py:258-262`):
per-step wall clock with warmup exclusion, images/sec, and an optional
``jax.profiler`` trace hook (SURVEY.md §5 "Tracing / profiling").

These helpers predate :mod:`tpudist.obs`; rather than keep two metric
systems, they now also report into the process-global obs registry
(:class:`ThroughputMeter` maintains ``throughput/items_per_sec`` and
``throughput/steps`` gauges; :class:`Stopwatch` can record laps into an
obs histogram via ``obs_name``).  The local Python API is unchanged —
callers that never touch obs see identical behavior.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax
import numpy as np


def _obs_registry():
    # lazy: utils must stay importable while obs is mid-import, and the
    # meters must keep working even if obs ever breaks
    try:
        from tpudist import obs

        return obs.registry
    except Exception:  # noqa: BLE001
        return None


class Stopwatch:
    """Wall-clock timer; ``block=True`` syncs outstanding device work first
    (async dispatch otherwise makes step timings meaningless).

    ``obs_name`` (e.g. ``"data/load_seconds"``) additionally records every
    :meth:`elapsed` reading into that obs histogram."""

    def __init__(self, obs_name: str | None = None) -> None:
        self._t0 = time.perf_counter()
        self._obs_name = obs_name

    def reset(self, block: bool = False) -> None:
        if block:
            jax.effects_barrier()
        self._t0 = time.perf_counter()

    def elapsed(self, block: bool = False) -> float:
        if block:
            jax.effects_barrier()
        dt = time.perf_counter() - self._t0
        if self._obs_name is not None:
            reg = _obs_registry()
            if reg is not None:
                reg.histogram(self._obs_name, unit="s").record(dt)
        return dt


class ThroughputMeter:
    """Images/sec (or items/sec) with warmup-step exclusion.

    Post-warmup steps also refresh the obs gauges
    ``throughput/items_per_sec`` and ``throughput/steps``, so the cluster
    view (and ``/metrics``) carries throughput without every call site
    exporting it by hand."""

    def __init__(self, warmup_steps: int = 1) -> None:
        self.warmup_steps = warmup_steps
        self._steps = 0
        self._items = 0
        self._elapsed = 0.0
        self._last: float | None = None
        self._rate_gauge = None
        self._steps_gauge = None

    def start(self) -> None:
        self._last = time.perf_counter()

    def step(self, n_items: int) -> None:
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return
        self._steps += 1
        if self._steps > self.warmup_steps:
            self._items += n_items
            self._elapsed += now - self._last
            if self._rate_gauge is None:
                reg = _obs_registry()
                if reg is not None:
                    self._rate_gauge = reg.gauge(
                        "throughput/items_per_sec", unit="items/s")
                    self._steps_gauge = reg.gauge(
                        "throughput/steps", unit="steps")
            if self._rate_gauge is not None:
                self._rate_gauge.set(self.items_per_sec)
                self._steps_gauge.set(float(self._steps))
        self._last = now

    @property
    def items_per_sec(self) -> float:
        return self._items / self._elapsed if self._elapsed else 0.0

    @property
    def mean_step_time(self) -> float:
        counted = self._steps - self.warmup_steps
        return self._elapsed / counted if counted > 0 else 0.0


class MetricLogger:
    """Running means of scalar metrics, flushed per epoch.

    Values may be device arrays: they are accumulated *lazily* (no ``float``
    per step, which would block on the async dispatch queue) and synced to
    host in one batch at :meth:`means` / :meth:`reset`."""

    def __init__(self) -> None:
        self._values: dict[str, list] = defaultdict(list)

    def update(self, **metrics) -> None:
        for k, v in metrics.items():
            self._values[k].append(v)

    def means(self) -> dict[str, float]:
        host = jax.device_get(dict(self._values))
        out = {}
        for k, vs in host.items():
            # entries may be scalars or stacked [n]-step arrays (the fused
            # train loop); flattening weights every step equally
            flat = np.concatenate(
                [np.atleast_1d(np.asarray(v, np.float64)) for v in vs])
            out[k] = float(flat.mean())
        return out

    def reset(self) -> dict[str, float]:
        out = self.means()
        self._values.clear()
        return out


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """``with maybe_profile("/tmp/trace"):`` wraps a region in a profiler
    trace viewable in XProf/TensorBoard; no-op when ``trace_dir`` is None."""
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield
