"""Pytree helpers shared across checkpointing and parallel layers."""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def tree_size_bytes(tree: Any) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def is_prng_key(x: Any) -> bool:
    return isinstance(x, jax.Array) and jax.dtypes.issubdtype(
        x.dtype, jax.dtypes.prng_key
    )


def leaf_to_host(x: Any) -> np.ndarray:
    """One leaf to host numpy; typed PRNG keys serialize as their raw
    key-data bits so checkpoints stay framework-object-free."""
    if is_prng_key(x):
        return np.asarray(jax.random.key_data(x))
    if isinstance(x, np.ndarray):
        return x.copy()
    return np.asarray(jax.device_get(x))


def host_to_leaf(template: Any, host: np.ndarray) -> Any:
    """Inverse of :func:`leaf_to_host`, typed by the template leaf: raw key
    bits are re-wrapped into a typed key with the template's impl."""
    if is_prng_key(template):
        return jax.random.wrap_key_data(
            jax.numpy.asarray(host), impl=jax.random.key_impl(template)
        )
    return host


def tree_to_numpy(tree: Any) -> Any:
    """Device→host copy of every leaf.  Host-numpy leaves are copied too, so
    the result never aliases caller-mutable memory (async checkpointing
    depends on this)."""
    return jax.tree.map(leaf_to_host, tree)


def flatten_with_names(tree: Any) -> dict[str, np.ndarray]:
    """Flatten a pytree to ``{path: leaf}`` with stable, human-readable keys
    (used as npz archive member names by the checkpointer)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(_path_str(p) for p in path) or "."
        out[key] = np.asarray(leaf)
    return out


def _path_str(entry: Any) -> str:
    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return str(entry.name)
    if isinstance(entry, jax.tree_util.FlattenedIndexKey):
        return str(entry.key)
    return str(entry)


def unflatten_like(template: Any, named: dict[str, np.ndarray]) -> Any:
    """Inverse of :func:`flatten_with_names` given a structural template."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        key = "/".join(_path_str(p) for p in path) or "."
        if key not in named:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        val = named[key]
        if is_prng_key(leaf):
            leaves.append(host_to_leaf(leaf, val))
            continue
        if tuple(val.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint leaf {key!r} shape {val.shape} != template {np.shape(leaf)}"
            )
        leaves.append(val.astype(np.asarray(leaf).dtype, copy=False))
    return jax.tree_util.tree_unflatten(treedef, leaves)
